"""Device-trace plane: cluster-wide ``jax.profiler`` capture with
step attribution and unified host+device timelines.

The host sampling profiler (util/profiler.py) attributes a stall to
"stuck in jitted step N" and then goes blind — everything inside the
XLA program is opaque, which is exactly where a TPU-native runtime
spends its time. Production TPU work is profile-driven: both the
pjit/TPUv4 training study (arXiv:2204.06514) and TPU serving
evaluations diagnose step-time regressions from device traces, not
host stacks. This module is the device half:

- **capture** — ``capture(duration_s)`` wraps
  ``jax.profiler.start_trace``/``stop_trace`` for a bounded window and
  parses the emitted ``trace.json.gz`` (perfetto/chrome-trace JSON, so
  no TF/XPlane proto deps) into timeline lanes, a per-op table and a
  per-step breakdown. One capture at a time per process; a concurrent
  request is rejected with a clear error, never queued. A light host
  lane sampler runs alongside so the unified timeline shows host
  threads and device ops on one time axis.
- **step attribution** — the train session reports every step-phase
  transition here (``note_phase``), building a wall-clock ring of
  ``{step, phase, rank, t0, t1}`` windows; each parsed device span is
  attributed by midpoint to "step N / compile|execute", giving every
  train rank a ``{step, compile_ms, execute_ms, gap_ms, top_ops}``
  breakdown.
- **cluster wiring** — ``device_trace_capture`` RPC on CoreWorker and
  the node agent (off-loop), ``device_trace_capture_cluster`` head
  fan-out with worker|task|actor|all targeting,
  ``ray_tpu profile --device``, dashboard ``GET /trace``, and a
  ``trace/`` section in ``write_debug_bundle``.
- **memory census** — ``device_memory_census()``: per-device
  ``memory_stats()`` where the backend provides it (graceful ``null``
  on CPU) plus a live-array census (count/bytes by sharding) from the
  device object registry.

Everything works under ``JAX_PLATFORMS=cpu``: the CPU backend emits
XLA op events (``args.hlo_op``) on its client threads too, so the
whole plane is tier-1 testable without a TPU.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import shutil
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: Device-op spans kept per parsed trace (longest first); the python
#: helper lane jax traces alongside is dropped entirely.
MAX_LANE_EVENTS = 3000
#: Host-lane spans kept per capture.
MAX_HOST_SPANS = 2000
#: Step-phase windows retained per process.
MAX_PHASE_WINDOWS = 4096
#: Rows in the per-op aggregate table.
DEFAULT_TOP_K = 25

_GZIP_MAGIC = b"\x1f\x8b"


def _config():
    try:
        from ray_tpu.core.config import get_config

        return get_config()
    except Exception:  # config not bootstrapped (bare tools)
        return None


def _default_out_dir() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR")
    if base:
        return os.path.join(base, "device_trace")
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "device_trace")


# ---------------------------------------------------------------------------
# step-phase window recorder (fed by train/session.py set_phase)
# ---------------------------------------------------------------------------

_phase_lock = threading.Lock()
_phase_windows: deque = deque(maxlen=MAX_PHASE_WINDOWS)
_phase_open: Optional[dict] = None
_step_counter = 0


def note_phase(phase: str, rank: Optional[int] = None) -> None:
    """Record a step-phase transition (train session ``set_phase``
    hook). Closes the open window, appends it to the ring, and advances
    the step counter when a ``step`` window closes — so a window's
    ``step`` is the index of the train step it belongs to (the compile
    window for step N precedes step N's execute window)."""
    global _phase_open, _step_counter
    now = time.time()
    with _phase_lock:
        prev = _phase_open
        if prev is not None:
            prev["t1"] = now
            _phase_windows.append(prev)
            if prev["phase"] == "step":
                _step_counter += 1
        if rank is None and prev is not None:
            rank = prev.get("rank")
        _phase_open = (
            {"phase": phase, "t0": now, "t1": None,
             "step": _step_counter, "rank": rank}
            if phase else None)


def phase_windows(t0: float, t1: float) -> List[dict]:
    """Closed windows overlapping ``[t0, t1]`` (wall clock), the open
    window clipped to now. Each: ``{phase, step, rank, t0, t1}``."""
    now = time.time()
    with _phase_lock:
        wins = [dict(w) for w in _phase_windows]
        if _phase_open is not None:
            wins.append(dict(_phase_open, t1=now))
    return [w for w in wins if w["t1"] > t0 and w["t0"] < t1]


def current_step() -> int:
    with _phase_lock:
        return _step_counter


def reset_phase_windows_for_testing() -> None:
    global _phase_open, _step_counter
    with _phase_lock:
        _phase_windows.clear()
        _phase_open = None
        _step_counter = 0


@contextlib.contextmanager
def step_phase(phase: str, rank: int = 0):
    """Standalone phase marker for code running OUTSIDE a train
    session (the train session routes its own ``set_phase`` here)."""
    note_phase(phase, rank)
    try:
        yield
    finally:
        note_phase("", rank)


def instrument_step(step_fn, rank: int = 0):
    """Wrap a (jitted) step callable: first call attributed to
    ``compile`` (jit traces + XLA compiles there), later calls to
    ``step`` — the session-free twin of train.instrument_step."""
    state = {"compiled": False}

    def wrapped(*args, **kwargs):
        with step_phase("step" if state["compiled"] else "compile",
                        rank):
            out = step_fn(*args, **kwargs)
        state["compiled"] = True
        return out

    return wrapped


# ---------------------------------------------------------------------------
# host lane sampler (time-resolved host spans for the unified timeline)
# ---------------------------------------------------------------------------

class _HostLaneSampler(threading.Thread):
    """Low-Hz top-of-stack sampler running only for the capture window:
    consecutive sweeps where a thread shows the same leaf frame merge
    into one span, so the unified timeline gets ``host:<pid>:<thread>``
    lanes without a second always-on profiler."""

    def __init__(self, hz: float = 25.0):
        super().__init__(daemon=True, name="rtpu-trace-host")
        self.interval = 1.0 / min(max(float(hz), 1.0), 100.0)
        self._stop = threading.Event()
        #: (ts, {ident: (thread_name, leaf)})
        self._sweeps: List[tuple] = []

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval):
            names = {t.ident: t.name for t in threading.enumerate()}
            now = time.time()
            seen: Dict[int, tuple] = {}
            for ident, frame in sys._current_frames().items():
                if ident == me or len(seen) >= 32:
                    continue
                try:
                    code = frame.f_code
                    leaf = (f"{code.co_filename.rsplit('/', 1)[-1]}"
                            f":{code.co_name}")
                except Exception:  # lint: allow-silent(frame freed mid-read — skip one sample)
                    continue
                seen[ident] = (names.get(ident, str(ident)), leaf)
            self._sweeps.append((now, seen))

    def lanes(self) -> List[dict]:
        """Merge sweeps into telemetry-format lane events
        (``{cat, name, ts, dur, args}``, seconds wall clock)."""
        pid = os.getpid()
        spans: List[dict] = []
        open_spans: Dict[int, dict] = {}
        for ts, seen in self._sweeps:
            for ident, span in list(open_spans.items()):
                cur = seen.get(ident)
                if cur is None or cur[1] != span["name"]:
                    span["dur"] = max(ts - span["ts"], self.interval)
                    spans.append(span)
                    del open_spans[ident]
            for ident, (tname, leaf) in seen.items():
                if ident not in open_spans:
                    open_spans[ident] = {
                        "cat": f"host:{pid}:{tname}", "name": leaf,
                        "ts": ts, "args": {"thread": tname}}
        tail = self._sweeps[-1][0] if self._sweeps else time.time()
        for span in open_spans.values():
            span["dur"] = max(tail - span["ts"], self.interval)
            spans.append(span)
        if len(spans) > MAX_HOST_SPANS:
            spans.sort(key=lambda s: -s["dur"])
            spans = spans[:MAX_HOST_SPANS]
        spans.sort(key=lambda s: s["ts"])
        return spans


# ---------------------------------------------------------------------------
# trace parser
# ---------------------------------------------------------------------------

def _demangle(name: str) -> str:
    """XLA op instance -> op kind: strip the leading ``%`` and the
    trailing instance counter (``loop_fusion.123`` -> ``loop_fusion``)."""
    return re.sub(r"\.\d+$", "", name.lstrip("%"))


def _load_trace_json(data) -> dict:
    """bytes (gz or plain JSON) or a path -> the trace dict. Raises
    ValueError with a diagnosable message on any corruption."""
    if isinstance(data, str):
        try:
            with open(data, "rb") as f:
                data = f.read()
        except OSError as e:
            raise ValueError(f"trace unreadable: {e}") from e
    if not isinstance(data, (bytes, bytearray)):
        raise ValueError(f"trace input must be bytes or a path, "
                         f"got {type(data).__name__}")
    raw = bytes(data)
    if raw[:2] == _GZIP_MAGIC:
        try:
            raw = gzip.decompress(raw)
        except Exception as e:
            raise ValueError(f"trace gzip corrupt: {e}") from e
    try:
        doc = json.loads(raw.decode("utf-8", errors="replace"))
    except Exception as e:
        raise ValueError(f"trace JSON corrupt: {e}") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace JSON has no traceEvents list")
    return doc


def _self_times(events: List[dict]) -> Dict[int, float]:
    """``id(event) -> self duration`` (dur minus directly nested child
    durs) per (pid, tid) span stack — "top ops by SELF device time"
    must not double-count a fusion inside its parent thunk."""
    by_tid: Dict[tuple, List[dict]] = {}
    for ev in events:
        by_tid.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    child_sum: Dict[int, float] = {}
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: List[dict] = []
        for ev in evs:
            end = ev["ts"] + ev.get("dur", 0.0)
            while stack and (stack[-1]["ts"]
                             + stack[-1].get("dur", 0.0)) <= ev["ts"]:
                stack.pop()
            if stack:
                child_sum[id(stack[-1])] = (
                    child_sum.get(id(stack[-1]), 0.0)
                    + ev.get("dur", 0.0))
            stack.append(ev)
    return {id(ev): max(0.0, ev.get("dur", 0.0)
                        - child_sum.get(id(ev), 0.0))
            for ev in events}


def parse_trace(data, t0_wall: float = 0.0,
                windows: Optional[List[dict]] = None,
                pid: Optional[int] = None,
                top_k: int = DEFAULT_TOP_K) -> dict:
    """Parse a jax.profiler ``trace.json.gz`` (bytes or path) into

    - ``lanes`` — timeline lane events (``device:<pid>`` XLA op spans,
      ``device:<pid>:compile`` codegen spans), wall-clock anchored at
      ``t0_wall`` (the moment ``start_trace`` returned),
    - ``ops`` — the per-op aggregate (top-K by self device time,
      compile vs execute split, fusion names demangled),
    - ``steps`` — the per-(rank, step) breakdown against the step-phase
      ``windows`` (``{step, rank, compile_ms, execute_ms, gap_ms,
      wall_ms, top_ops}``),
    - ``summary`` — event counts and total compile/execute time.

    A truncated/corrupt trace returns a structured ``{"error": ...}``
    entry — never an exception (chaos contract: a SIGKILL mid-write
    must not crash the merge)."""
    pid = os.getpid() if pid is None else pid
    try:
        doc = _load_trace_json(data)
    except ValueError as e:
        return {"error": str(e), "ops": [], "steps": [], "lanes": [],
                "summary": {}}

    thread_names: Dict[tuple, str] = {}
    process_names: Dict[Any, str] = {}
    device_ops: List[dict] = []
    compile_evs: List[dict] = []
    n_python = n_events = 0
    base = None  # trace-clock origin == the moment start_trace ran
    for ev in doc["traceEvents"]:
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        if ph == "M":
            args = ev.get("args") or {}
            if ev.get("name") == "thread_name":
                thread_names[(ev.get("pid"), ev.get("tid"))] = \
                    str(args.get("name", ""))
            elif ev.get("name") == "process_name":
                process_names[ev.get("pid")] = str(args.get("name", ""))
            continue
        if ph != "X":
            continue
        n_events += 1
        ts = ev.get("ts")
        if isinstance(ts, (int, float)) and (base is None or ts < base):
            # Anchor on the EARLIEST event of any kind: the python
            # start_trace event sits at ~0 on the trace clock, while
            # the first device op can land arbitrarily late — so the
            # minimum over device events alone would skew every
            # wall-clock mapping by that lead time.
            base = float(ts)
        name = str(ev.get("name", ""))
        if name.startswith("$"):
            # jax's own python-level tracer: tens of thousands of
            # events that duplicate what the host sampler already
            # shows, time-skewed. Drop them wholesale.
            n_python += 1
            continue
        try:
            ev["ts"] = float(ev.get("ts", 0.0))
            ev["dur"] = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        args = ev.get("args") or {}
        tname = thread_names.get((ev.get("pid"), ev.get("tid")), "")
        pname = process_names.get(ev.get("pid"), "")
        if ("hlo_op" in args or "hlo_module" in args
                or pname.startswith("/device:")):
            device_ops.append(ev)
        elif "codegen" in tname.lower() or "compil" in tname.lower():
            compile_evs.append(ev)

    if base is None:
        base = min((e["ts"] for e in device_ops + compile_evs),
                   default=0.0)
    self_us = _self_times(device_ops)

    # -- per-op aggregate ------------------------------------------------
    table: Dict[str, dict] = {}
    for ev in device_ops:
        op = _demangle(str((ev.get("args") or {}).get("hlo_op")
                           or ev.get("name", "?")))
        row = table.setdefault(op, {"op": op, "count": 0,
                                    "self_us": 0.0, "total_us": 0.0,
                                    "phase": "execute"})
        row["count"] += 1
        row["self_us"] += self_us.get(id(ev), 0.0)
        row["total_us"] += ev["dur"]
    compile_us = sum(e["dur"] for e in compile_evs)
    execute_us = sum(self_us.values())
    ops = sorted(table.values(), key=lambda r: -r["self_us"])[:top_k]
    for row in ops:
        row["self_us"] = round(row["self_us"], 1)
        row["total_us"] = round(row["total_us"], 1)

    # -- step attribution ------------------------------------------------
    windows = sorted(windows or [], key=lambda w: w["t0"])
    steps: Dict[tuple, dict] = {}
    unattributed_us = 0.0

    def _window_for(mid: float) -> Optional[dict]:
        for w in windows:
            if w["t0"] <= mid < w["t1"]:
                return w
        return None

    for ev, dur_us, kind in (
            [(e, self_us.get(id(e), 0.0), "op") for e in device_ops]
            + [(e, e["dur"], "compile") for e in compile_evs]):
        mid = t0_wall + (ev["ts"] - base + ev["dur"] / 2.0) / 1e6
        w = _window_for(mid)
        if w is None:
            unattributed_us += dur_us
            continue
        key = (w.get("rank") or 0, w["step"])
        row = steps.setdefault(key, {
            "rank": key[0], "step": key[1], "compile_ms": 0.0,
            "execute_ms": 0.0, "wall_ms": 0.0, "gap_ms": 0.0,
            "top_ops": {}})
        if w["phase"] == "compile" or kind == "compile":
            row["compile_ms"] += dur_us / 1e3
        else:
            row["execute_ms"] += dur_us / 1e3
        if kind == "op":
            op = _demangle(str((ev.get("args") or {}).get("hlo_op")
                               or ev.get("name", "?")))
            row["top_ops"][op] = row["top_ops"].get(op, 0.0) + dur_us / 1e3
    for w in windows:
        key = (w.get("rank") or 0, w["step"])
        if key in steps:
            steps[key]["wall_ms"] += (w["t1"] - w["t0"]) * 1e3
    step_rows = []
    for row in sorted(steps.values(),
                      key=lambda r: (r["rank"], r["step"])):
        row["gap_ms"] = round(max(
            0.0, row["wall_ms"] - row["compile_ms"] - row["execute_ms"]),
            2)
        row["top_ops"] = [[op, round(ms, 2)] for op, ms in sorted(
            row["top_ops"].items(), key=lambda kv: -kv[1])[:5]]
        for k in ("compile_ms", "execute_ms", "wall_ms"):
            row[k] = round(row[k], 2)
        step_rows.append(row)

    # -- timeline lanes --------------------------------------------------
    keep = device_ops + compile_evs
    if len(keep) > MAX_LANE_EVENTS:
        keep = sorted(keep, key=lambda e: -e["dur"])[:MAX_LANE_EVENTS]
    lanes = []
    compile_ids = {id(e) for e in compile_evs}
    for ev in sorted(keep, key=lambda e: e["ts"]):
        args = ev.get("args") or {}
        cat = (f"device:{pid}:compile" if id(ev) in compile_ids
               else f"device:{pid}")
        lanes.append({
            "cat": cat,
            "name": str(args.get("hlo_op") or ev.get("name", "?")),
            "ts": t0_wall + (ev["ts"] - base) / 1e6,
            "dur": ev["dur"] / 1e6,
            "args": {k: v for k, v in args.items()
                     if k in ("hlo_op", "hlo_module")},
        })

    return {
        "ops": ops,
        "steps": step_rows,
        "lanes": lanes,
        "summary": {
            "events": n_events,
            "device_events": len(device_ops),
            "compile_events": len(compile_evs),
            "python_events_dropped": n_python,
            "execute_us": round(execute_us, 1),
            "compile_us": round(compile_us, 1),
            "unattributed_us": round(unattributed_us, 1),
        },
    }


# ---------------------------------------------------------------------------
# device-memory census
# ---------------------------------------------------------------------------

def device_memory_census() -> dict:
    """Per-device ``memory_stats()`` where the backend provides it
    (``null`` on CPU — the CPU client reports none) plus a live-array
    census by sharding from the device object registry."""
    out: dict = {"devices": [],
                 "arrays": {"count": 0, "bytes": 0, "by_sharding": {}}}
    try:
        import jax

        for d in jax.devices():
            try:
                stats = d.memory_stats()
            except Exception:  # backend without the API (== null)
                stats = None
            out["devices"].append({
                "id": int(d.id), "platform": str(d.platform),
                "memory_stats": stats})
    except Exception as e:  # noqa: BLE001 — census degrades, never raises
        out["devices_error"] = f"{type(e).__name__}: {e}"
    try:
        from ray_tpu.core import device_objects as dobj

        by_sharding = out["arrays"]["by_sharding"]
        with dobj._registry_lock:
            for entry in dobj._registry.values():
                for le in entry.leaves.values():
                    desc = le.desc or {}
                    if desc.get("kind") == "named":
                        key = (f"named[{','.join(desc.get('mesh_axes') or ())}"
                               f"={'x'.join(map(str, desc.get('mesh_shape') or ()))}]"
                               f" {json.dumps(desc.get('spec'))}")
                    else:
                        key = desc.get("kind") or "?"
                    row = by_sharding.setdefault(
                        key, {"count": 0, "bytes": 0})
                    row["count"] += 1
                    row["bytes"] += int(le.nbytes or 0)
                    out["arrays"]["count"] += 1
                    out["arrays"]["bytes"] += int(le.nbytes or 0)
    except Exception as e:  # noqa: BLE001
        out["arrays_error"] = f"{type(e).__name__}: {e}"
    return out


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------

_capture_lock = threading.Lock()


def _capture_failed(msg: str, status: str = "error") -> dict:
    from ray_tpu.util import flight_recorder, telemetry

    telemetry.inc("ray_tpu_device_trace_captures_total", 1,
                  {"status": status})
    flight_recorder.record("trace", "capture_failed",
                           severity=flight_recorder.WARN,
                           reason=msg[:200])
    return {"pid": os.getpid(), "ts": time.time(), "error": msg}


def capture(duration_s: float = 2.0, out_dir: Optional[str] = None,
            host_hz: float = 25.0) -> dict:
    """One bounded device-trace window over THIS process. Blocks for
    ``duration_s`` (RPC handlers run it in an executor). Returns the
    parsed reply — raw gz bytes (``trace_gz``), per-op table, per-step
    breakdown, device + host lanes, memory census — or a structured
    ``{"error": ...}`` entry (concurrent capture, jax missing, trace
    over the byte cap)."""
    cfg = _config()
    max_duration = (cfg.device_trace_max_duration_s
                    if cfg is not None else 60.0)
    max_bytes = (cfg.device_trace_max_trace_bytes
                 if cfg is not None else 64 * 1024 * 1024)
    duration_s = min(max(float(duration_s), 0.05), float(max_duration))
    if not _capture_lock.acquire(blocking=False):
        return _capture_failed(
            "device-trace capture already in progress in "
            f"pid {os.getpid()} — one capture at a time per process",
            status="rejected")
    tmpdir = tempfile.mkdtemp(prefix="rtpu-devtrace-")
    sampler = _HostLaneSampler(hz=host_hz)
    try:
        try:
            import jax
        except Exception as e:  # noqa: BLE001
            return _capture_failed(f"jax unavailable: {e}")
        sampler.start()
        t0 = time.time()
        try:
            jax.profiler.start_trace(tmpdir)
        except Exception as e:  # noqa: BLE001
            return _capture_failed(f"start_trace failed: {e}")
        try:
            time.sleep(duration_s)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                return _capture_failed(f"stop_trace failed: {e}")
        t1 = time.time()
        sampler.stop()
        paths = glob.glob(os.path.join(
            tmpdir, "**", "*.trace.json.gz"), recursive=True)
        if not paths:
            return _capture_failed("no trace.json.gz produced by "
                                   "jax.profiler")
        with open(paths[0], "rb") as f:
            raw = f.read()
        if len(raw) > int(max_bytes):
            return _capture_failed(
                f"trace file too large ({len(raw)} > "
                f"device_trace_max_trace_bytes={int(max_bytes)}); "
                "shorten the capture window")
        parsed = parse_trace(raw, t0_wall=t0,
                             windows=phase_windows(t0, t1))
        if parsed.get("error"):
            return _capture_failed(f"trace parse failed: "
                                   f"{parsed['error']}")
        retained = _retain_trace(raw, t0, out_dir)
        _record_capture_metrics(len(raw), parsed["steps"])
        from ray_tpu.util import flight_recorder

        flight_recorder.record(
            "trace", "captured", duration_s=round(t1 - t0, 3),
            bytes=len(raw), ops=len(parsed["ops"]),
            steps=len(parsed["steps"]),
            device_events=parsed["summary"].get("device_events", 0))
        return {
            "pid": os.getpid(),
            "ts": t0,
            "t0": t0,
            "t1": t1,
            "duration_s": round(t1 - t0, 4),
            "trace_bytes": len(raw),
            "trace_gz": raw,
            "trace_path": retained,
            "host_lanes": sampler.lanes(),
            "census": device_memory_census(),
            **parsed,
        }
    except Exception as e:  # noqa: BLE001 — the RPC must answer, not die
        return _capture_failed(f"{type(e).__name__}: {e}")
    finally:
        sampler.stop()
        shutil.rmtree(tmpdir, ignore_errors=True)
        _capture_lock.release()


def _retain_trace(raw: bytes, t0: float,
                  out_dir: Optional[str]) -> Optional[str]:
    """Keep the raw trace in the session's device_trace dir (rotated
    under the retention flags) for post-hoc Perfetto loading."""
    out_dir = out_dir or _default_out_dir()
    try:
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"trace-{os.getpid()}-{int(t0)}.json.gz")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)
        cfg = _config()
        if cfg is not None:
            from ray_tpu.util.profiler import rotate_dir

            rotate_dir(out_dir, cfg.device_trace_retain_files,
                       cfg.device_trace_retain_bytes, keep=(path,))
        return path
    except OSError:  # lint: allow-silent(retention is best-effort; the reply already carries the bytes)
        return None


def _record_capture_metrics(nbytes: int, steps: List[dict]) -> None:
    from ray_tpu.util import telemetry

    telemetry.inc("ray_tpu_device_trace_captures_total", 1,
                  {"status": "ok"})
    telemetry.set_gauge("ray_tpu_device_trace_bytes", nbytes,
                        {"proc": telemetry.proc_tag()})
    for row in steps:
        tags = {"rank": str(row["rank"])}
        if row["execute_ms"] > 0:
            telemetry.observe("ray_tpu_train_step_device_time_seconds",
                              row["execute_ms"] / 1e3,
                              dict(tags, phase="execute"))
        if row["compile_ms"] > 0:
            telemetry.observe("ray_tpu_train_step_device_time_seconds",
                              row["compile_ms"] / 1e3,
                              dict(tags, phase="compile"))


# ---------------------------------------------------------------------------
# driver-side veneer (cluster fan-out + file outputs)
# ---------------------------------------------------------------------------

def capture_cluster(kind: str = "all", ident: Optional[str] = None,
                    duration_s: float = 2.0,
                    timeout_s: float = 30.0) -> dict:
    """Fan ``device_trace_capture`` out over the cluster (head handler
    ``device_trace_capture_cluster``), same targeting grammar as the
    host profiler: worker | task | actor | all."""
    from ray_tpu.util.state import _call

    return _call("device_trace_capture_cluster", {
        "kind": kind,
        "id": (ident or "").lower(),
        "duration_s": duration_s,
        "timeout_s": timeout_s,
    })


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in name)


def entry_json(entry: dict) -> dict:
    """A capture entry without the raw gz bytes (JSON surfaces)."""
    return {k: v for k, v in entry.items() if k != "trace_gz"}


def merged_timeline_events(entries: List[dict]) -> List[dict]:
    """Chrome-trace events merging every source's device + host lanes,
    plus this driver's telemetry lanes (``train/step:r<rank>``,
    ``profile:<pid>``) clipped to the capture window — host flamegraph
    lanes and device-op lanes on one wall-clock axis."""
    from ray_tpu.util.timeline import telemetry_trace_events

    lane_events: List[dict] = []
    t_lo, t_hi = float("inf"), 0.0
    for entry in entries:
        if entry.get("error"):
            continue
        lane_events.extend(entry.get("lanes") or [])
        lane_events.extend(entry.get("host_lanes") or [])
        t_lo = min(t_lo, entry.get("t0") or float("inf"))
        t_hi = max(t_hi, entry.get("t1") or 0.0)
    try:
        from ray_tpu.util import telemetry

        try:
            merged = telemetry.collect_timeline_events()
        except Exception:
            merged = telemetry.local_timeline_events()
        if t_lo < t_hi:
            merged = [ev for ev in merged
                      if t_lo - 5.0 <= float(ev.get("ts", 0.0))
                      <= t_hi + 5.0]
        lane_events.extend(merged)
    except Exception:  # lint: allow-silent(telemetry lanes are decoration on the device view)
        pass
    return telemetry_trace_events(lane_events)


def write_trace_outputs(reply: dict, out_dir: str,
                        title: str = "ray_tpu device trace") -> dict:
    """Write a capture-cluster reply as files: per-source
    ``<source>.trace.json.gz`` (Perfetto-loadable raw trace) +
    ``<source>.ops.json`` (per-op table, per-step breakdown, census),
    a merged ``timeline.json`` (chrome-trace) + ``timeline.html``
    (unified host+device view), and a ``trace.json`` manifest."""
    os.makedirs(out_dir, exist_ok=True)
    manifest: Dict[str, Any] = {"sources": [], "errors": {},
                                "steps": [], "device_events": 0}
    entries = reply.get("entries", [])
    for entry in entries:
        source = entry.get("source") or f"pid:{entry.get('pid', '?')}"
        safe = _sanitize(source)
        if entry.get("error"):
            manifest["errors"][safe] = entry["error"]
            continue
        manifest["sources"].append(source)
        manifest["device_events"] += (entry.get("summary") or {}).get(
            "device_events", 0)
        raw = entry.get("trace_gz")
        if raw:
            with open(os.path.join(out_dir, f"{safe}.trace.json.gz"),
                      "wb") as f:
                f.write(raw)
        with open(os.path.join(out_dir, f"{safe}.ops.json"), "w") as f:
            json.dump({k: entry.get(k) for k in
                       ("source", "pid", "node_id", "t0", "t1",
                        "duration_s", "trace_bytes", "ops", "steps",
                        "summary", "census")},
                      f, indent=1, default=str)
        for row in entry.get("steps") or []:
            manifest["steps"].append(dict(row, source=source))
    events = merged_timeline_events(entries)
    with open(os.path.join(out_dir, "timeline.json"), "w") as f:
        json.dump(events, f)
    html_path = os.path.join(out_dir, "timeline.html")
    with open(html_path, "w") as f:
        f.write(unified_timeline_html(events, title=title))
    manifest["timeline"] = html_path
    with open(os.path.join(out_dir, "trace.json"), "w") as f:
        json.dump(dict(manifest, reply_ts=reply.get("ts")), f,
                  indent=1, default=str)
    return manifest


# ---------------------------------------------------------------------------
# unified timeline HTML
# ---------------------------------------------------------------------------

_TIMELINE_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>%(title)s</title><style>
body{font:12px monospace;margin:0;background:#1b1b1f;color:#ddd}
#hdr{padding:8px 12px;border-bottom:1px solid #333}
.lane{display:flex;align-items:center;height:20px;margin:1px 0}
.label{width:280px;flex:none;overflow:hidden;white-space:nowrap;
 text-overflow:ellipsis;color:#9a9;padding-right:8px;text-align:right}
.track{position:relative;flex:1;height:16px;background:#232327;
 border-radius:2px}
.sp{position:absolute;top:1px;height:14px;min-width:1px;
 border-radius:1px;overflow:hidden;font-size:10px;color:#1b1b1f;
 cursor:default}
.sp:hover{filter:brightness(1.3)}
#axis{margin-left:280px;color:#667;padding:2px 0 8px 0}
</style></head><body>
<div id="hdr">%(title)s &mdash; %(nlanes)s lanes, %(nspans)s spans,
 %(window)s window (hover a span for op + timing)</div>
<div id="tl"></div><div id="axis"></div>
<script>
var DATA=%(data)s;
function color(cat){
 if(cat.indexOf('device:')===0)
   return cat.indexOf(':compile')>0?'hsl(45,80%%,60%%)'
                                   :'hsl(150,60%%,55%%)';
 if(cat.indexOf('host:')===0)return 'hsl(210,50%%,62%%)';
 if(cat.indexOf('train/step')===0)return 'hsl(20,75%%,62%%)';
 if(cat.indexOf('profile:')===0)return 'hsl(280,40%%,64%%)';
 var h=0;for(var i=0;i<cat.length;i++)h=(h*31+cat.charCodeAt(i))%%360;
 return 'hsl('+h+',55%%,60%%)';}
var tl=document.getElementById('tl');
var span=Math.max(DATA.t1-DATA.t0,1e-6);
DATA.lanes.forEach(function(lane){
 var row=document.createElement('div');row.className='lane';
 var lb=document.createElement('div');lb.className='label';
 lb.textContent=lane.name;lb.title=lane.name;row.appendChild(lb);
 var tr=document.createElement('div');tr.className='track';
 lane.spans.forEach(function(s){
   var el=document.createElement('div');el.className='sp';
   el.style.left=((s[0]-DATA.t0)/span*100)+'%%';
   el.style.width=Math.max(s[1]/span*100,0.05)+'%%';
   el.style.background=color(lane.name);
   el.title=s[2]+' ('+(s[1]*1000).toFixed(2)+' ms @ +'
     +((s[0]-DATA.t0)*1000).toFixed(1)+' ms)';
   if(s[1]/span>0.04)el.textContent=s[2];
   tr.appendChild(el);
 });
 row.appendChild(tr);tl.appendChild(row);
});
document.getElementById('axis').textContent=
 '0 ms'+Array(8).join('\\u2500\\u2500\\u2500\\u2500\\u2500')
 +(span*1000).toFixed(1)+' ms';
</script></body></html>
"""

#: Lane-name prefixes in display order: step markers first, then host
#: sampler lanes, then the device lanes they explain.
_LANE_ORDER = ("train/step", "task:", "profile:", "host:", "device:")


def _lane_rank(name: str) -> tuple:
    for i, prefix in enumerate(_LANE_ORDER):
        if name.startswith(prefix):
            return (i, name)
    return (len(_LANE_ORDER), name)


def unified_timeline_html(events: List[dict],
                          title: str = "ray_tpu device trace") -> str:
    """Self-contained HTML rendering chrome-trace events (one lane per
    ``tid``) on a single wall-clock axis: host sampler lanes next to
    ``device:<pid>`` XLA-op lanes. Names are attacker-influenced (task
    names, query params) — escaped out of HTML/script contexts."""
    import html as _html

    lanes: Dict[str, List[list]] = {}
    t0, t1 = float("inf"), 0.0
    for ev in events:
        if ev.get("ph") not in ("X", "B", "i"):
            continue
        ts = float(ev.get("ts", 0.0)) / 1e6
        dur = float(ev.get("dur", 0.0) or 0.0) / 1e6
        t0 = min(t0, ts)
        t1 = max(t1, ts + dur)
        lanes.setdefault(str(ev.get("tid", "?")), []).append(
            [round(ts, 6), round(dur, 6), str(ev.get("name", "?"))])
    if t0 > t1:
        t0, t1 = 0.0, 1.0
    lane_rows = [{"name": name, "spans": sorted(spans)}
                 for name, spans in sorted(
                     lanes.items(), key=lambda kv: _lane_rank(kv[0]))]
    data = json.dumps({"t0": t0, "t1": t1, "lanes": lane_rows})
    data = data.replace("<", "\\u003c")
    return _TIMELINE_TEMPLATE % {
        "title": _html.escape(title),
        "nlanes": len(lane_rows),
        "nspans": sum(len(r["spans"]) for r in lane_rows),
        "window": f"{(t1 - t0):.2f}s",
        "data": data,
    }
