"""Built-in runtime telemetry: the ``ray_tpu_*`` metric catalog.

Reference: Ray ships hundreds of built-in ``ray_*`` metrics
(python/ray/_private/metrics_agent.py + src/ray/stats/metric_defs.cc)
because a distributed runtime without telemetry cannot be operated at
scale. Here ONE module owns the namespace: every built-in metric is
declared in ``CATALOG`` and instantiated lazily on first record, so an
idle process pays nothing and the tier-1 catalog lint
(tests/test_telemetry_catalog.py) can statically verify that names are
unique, ``ray_tpu_``-prefixed, and carry only declared tag keys.

Hot-path contract: every recorder checks one cached ``enabled`` bool
first (``RAY_TPU_METRICS_ENABLED=0`` / ``system_config`` turns the whole
plane off), and instrumented modules import this module lazily so the
core bootstrap order is unchanged.

Alongside metrics, ``event()`` feeds a small per-process ring buffer of
timeline events (object transfers, retries, breaker trips) that rides
the metrics push throttle to the head KV; ``util/timeline.py`` merges
them into extra chrome-tracing lanes next to the task lanes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.util import metrics as _metrics

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# Sub-millisecond RPCs up to multi-second stragglers.
LATENCY_BOUNDARIES = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                      0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0]
# Serve/train paths: first-request jit compiles can take tens of seconds.
SLOW_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0]

#: name -> (type, description, tag_keys, histogram boundaries or None).
#: The single source of truth for the built-in namespace; the guard test
#: lints every ``ray_tpu_*`` registration against this table.
CATALOG: Dict[str, tuple] = {
    # --- rpc (core/rpc.py) ---
    "ray_tpu_rpc_client_latency_seconds": (
        HISTOGRAM, "Round-trip latency of RPC request/reply calls.",
        ("method",), LATENCY_BOUNDARIES),
    "ray_tpu_rpc_sent_bytes_total": (
        COUNTER, "Bytes written to RPC transports (frames + sidecars).",
        (), None),
    "ray_tpu_rpc_recv_bytes_total": (
        COUNTER, "Bytes read from RPC transports (frames + sidecars).",
        (), None),
    # Per-process gauge: the "proc" tag keeps each process's series
    # distinct — collect_metrics merges gauges last-write-wins per tag
    # set, so an untagged per-process gauge would collapse to whichever
    # process pushed last.
    "ray_tpu_rpc_in_flight_requests": (
        GAUGE, "RPC requests awaiting a reply, per process.",
        ("proc",), None),
    "ray_tpu_rpc_faults_injected_total": (
        COUNTER, "Frames matched by the network fault-injection plane.",
        ("action",), None),
    # --- unified retry / circuit breaker (core/retry.py) ---
    "ray_tpu_retries_total": (
        COUNTER, "Retries performed by the unified RetryPolicy.",
        ("site",), None),
    "ray_tpu_retry_backoff_seconds_total": (
        COUNTER, "Cumulative backoff delay slept before retries.",
        ("site",), None),
    "ray_tpu_retry_deadline_exhausted_total": (
        COUNTER, "Retry/poll envelopes that exhausted their deadline.",
        ("site",), None),
    "ray_tpu_circuit_breaker_transitions_total": (
        COUNTER, "Circuit-breaker state transitions.",
        ("state",), None),
    # --- scheduler (core/scheduler.py) ---
    "ray_tpu_scheduler_pending_leases": (
        GAUGE, "Lease requests parked in the cluster scheduler queue.",
        (), None),
    "ray_tpu_scheduler_leases_granted_total": (
        COUNTER, "Worker leases granted by the cluster scheduler.",
        (), None),
    "ray_tpu_scheduler_placement_latency_seconds": (
        HISTOGRAM, "Queue-to-grant latency of lease requests.",
        (), LATENCY_BOUNDARIES),
    # --- tasks (core/core_worker.py) ---
    "ray_tpu_tasks_total": (
        COUNTER, "Task state transitions observed by this process.",
        ("state",), None),
    # --- object plane (core/object_store.py, core/object_transfer.py) ---
    # Per-node gauges: every process on a node reports the same shared
    # arena, so last-write-wins per node tag is exactly right.
    "ray_tpu_object_store_used_bytes": (
        GAUGE, "Bytes used in the node shared-memory object store.",
        ("node",), None),
    "ray_tpu_object_store_objects": (
        GAUGE, "Objects resident in the node shared-memory store.",
        ("node",), None),
    "ray_tpu_object_spilled_total": (
        COUNTER, "Objects spilled to disk.", (), None),
    "ray_tpu_object_spilled_bytes_total": (
        COUNTER, "Bytes spilled to disk.", (), None),
    "ray_tpu_object_restored_total": (
        COUNTER, "Objects restored from spill files.", (), None),
    "ray_tpu_object_pull_seconds": (
        HISTOGRAM, "Latency of object pull sweeps across holders.",
        ("status",), SLOW_BOUNDARIES),
    # --- device-native object plane (core/device_objects.py) ---
    "ray_tpu_object_device_bytes": (
        GAUGE, "Device-resident bytes registered in this process's "
        "shard registry (exported puts + assembled borrows).",
        ("proc",), None),
    "ray_tpu_object_shard_pull_seconds": (
        HISTOGRAM, "Per-shard pull latency (device object plane), by "
        "transport path and outcome.",
        ("status",), SLOW_BOUNDARIES),
    "ray_tpu_object_shard_pull_bytes_total": (
        COUNTER, "Bytes landed by per-shard device-plane pulls.",
        (), None),
    # --- gcs (core/gcs.py) ---
    "ray_tpu_gcs_nodes": (
        GAUGE, "Cluster nodes by state (SUSPECT = death-grace window).",
        ("state",), None),
    # --- serve (serve/proxy.py, serve/router.py, serve/replica.py) ---
    "ray_tpu_serve_http_requests_total": (
        COUNTER, "HTTP requests handled by the Serve proxy.",
        ("route", "code"), None),
    "ray_tpu_serve_http_latency_seconds": (
        HISTOGRAM, "End-to-end Serve proxy HTTP request latency.",
        ("route",), SLOW_BOUNDARIES),
    # Routers are per-process (proxy, composing replicas, drivers):
    # the "proc" tag keeps their local queue views from clobbering each
    # other in the gauge merge.
    "ray_tpu_serve_router_queue_depth": (
        GAUGE, "Router-tracked ongoing requests per deployment.",
        ("deployment", "proc"), None),
    "ray_tpu_serve_request_latency_seconds": (
        HISTOGRAM, "Assign-to-completion latency of routed requests.",
        ("deployment",), SLOW_BOUNDARIES),
    "ray_tpu_serve_replica_sheds_total": (
        COUNTER, "Replicas shed from routing by an open breaker.",
        ("deployment",), None),
    "ray_tpu_serve_replica_requests_total": (
        COUNTER, "Requests executed by replicas.",
        ("deployment", "status"), None),
    "ray_tpu_serve_replica_latency_seconds": (
        HISTOGRAM, "Replica-side request execution latency.",
        ("deployment",), SLOW_BOUNDARIES),
    # --- serve streaming (serve/router.py + serve/proxy.py) ---
    "ray_tpu_serve_stream_ttft_seconds": (
        HISTOGRAM, "Time from stream assignment to the first chunk "
        "(time-to-first-token for LLM serving).",
        ("deployment",), SLOW_BOUNDARIES),
    "ray_tpu_serve_stream_chunks_total": (
        COUNTER, "Chunks produced by streaming deployment responses.",
        ("deployment",), None),
    "ray_tpu_serve_stream_aborts_total": (
        COUNTER, "Streams terminated before a clean finish "
        "(replica_death / client_disconnect / deadline / app_error).",
        ("deployment", "reason"), None),
    # --- serve continuous-batching engine (serve/engine/core.py) ---
    # Per-replica gauges ("proc" keeps each replica process's series
    # distinct through the last-write-wins gauge merge).
    "ray_tpu_serve_engine_batch_occupancy": (
        GAUGE, "Sequences currently decoding in a replica's "
        "continuous-batching engine.",
        ("deployment", "proc"), None),
    "ray_tpu_serve_engine_queue_depth": (
        GAUGE, "Requests parked in a replica engine's admission queue.",
        ("deployment", "proc"), None),
    "ray_tpu_serve_engine_queue_wait_seconds": (
        HISTOGRAM, "Admission-queue wait (submit to batch admission) "
        "of engine requests.",
        ("deployment",), SLOW_BOUNDARIES),
    # --- serve autoscaling (serve/controller.py) ---
    "ray_tpu_serve_autoscale_decisions_total": (
        COUNTER, "Replica-target changes made by the deployment "
        "autoscaler (direction up/down; reason ttft / queue_depth / "
        "ongoing / idle / pending_requests).",
        ("deployment", "direction", "reason"), None),
    # --- serve batching (serve/batching.py) ---
    "ray_tpu_serve_batch_queue_wait_seconds": (
        HISTOGRAM, "Time @serve.batch requests spend parked before "
        "their batch flushes.",
        (), LATENCY_BOUNDARIES),
    # --- live profiling plane (util/profiler.py) ---
    "ray_tpu_profiler_samples_total": (
        COUNTER, "Stack samples taken by the sampling profiler "
        "(on_demand captures / the continuous background sampler).",
        ("mode",), None),
    "ray_tpu_profiler_overhead_ratio": (
        GAUGE, "Measured sampling overhead of the continuous profiler "
        "(sampling time / wall time), per process.",
        ("proc",), None),
    # --- train (train/session.py) ---
    "ray_tpu_train_reports_total": (
        COUNTER, "train.report() calls across training workers.",
        (), None),
    "ray_tpu_train_step_seconds": (
        HISTOGRAM, "Wall time between consecutive train.report() calls.",
        (), SLOW_BOUNDARIES),
    # --- train recovery (train/backend_executor.py, train/trainer.py,
    # train/checkpoint_manager.py, tune/tune_controller.py) ---
    # Per-rank staleness of the device step-counter heartbeat (seconds
    # since the rank's step counter last advanced); the gang monitor
    # sets it each sweep, so dashboards see a hang *growing* before the
    # abort fires. "rank" keeps the per-rank series distinct through
    # the last-write-wins gauge merge.
    "ray_tpu_train_step_heartbeat_age_seconds": (
        GAUGE, "Seconds since each rank's train step counter last "
        "advanced, as observed by the gang health monitor.",
        ("rank",), None),
    "ray_tpu_train_restarts_total": (
        COUNTER, "Gang restarts performed by the trainer, by failure "
        "kind (died / hung / unresponsive / error).",
        ("reason",), None),
    "ray_tpu_train_hang_detections_total": (
        COUNTER, "Ranks declared hung by the gang health monitor "
        "(no progress past hang_timeout_s).", (), None),
    "ray_tpu_train_worker_deaths_total": (
        COUNTER, "Train worker actor deaths observed by the gang "
        "health monitor or the report stream.", (), None),
    "ray_tpu_train_torn_checkpoint_skips_total": (
        COUNTER, "Checkpoint directories skipped during recovery for a "
        "missing/invalid COMMIT marker or truncated shard.", (), None),
    "ray_tpu_train_elastic_resizes_total": (
        COUNTER, "Gang re-formations at a smaller world size after "
        "resources failed to return.", (), None),
    "ray_tpu_tune_trial_retries_total": (
        COUNTER, "Failed Tune trials restarted from their latest "
        "checkpoint under RunConfig.failure_config.", (), None),
    # --- cluster health plane (core/health.py, util/metrics_history.py,
    # util/alerts.py) ---
    "ray_tpu_metrics_history_series": (
        GAUGE, "Live series in the head-side metrics history store.",
        (), None),
    "ray_tpu_metrics_history_bytes": (
        GAUGE, "Approximate bytes held by the metrics history store.",
        (), None),
    "ray_tpu_metrics_history_evictions_total": (
        COUNTER, "Series evicted whole from the history store by the "
        "hard byte cap (least-recently-updated first).", (), None),
    "ray_tpu_alerts_firing": (
        GAUGE, "Alert series currently firing, per rule.",
        ("rule",), None),
    "ray_tpu_alerts_transitions_total": (
        COUNTER, "Alert lifecycle transitions (state fired/resolved).",
        ("rule", "state"), None),
    # --- device trace plane (util/device_trace.py) ---
    "ray_tpu_device_trace_captures_total": (
        COUNTER, "Device-trace capture windows, by outcome "
        "(ok / error / rejected-concurrent).", ("status",), None),
    "ray_tpu_device_trace_bytes": (
        GAUGE, "Size of the last device trace file captured by this "
        "process.", ("proc",), None),
    "ray_tpu_train_step_device_time_seconds": (
        HISTOGRAM, "Device time attributed to one train step by the "
        "device-trace parser, split by phase (compile / execute) and "
        "rank.", ("rank", "phase"), SLOW_BOUNDARIES),
    # --- control-plane load observatory (util/rpc_stats.py,
    # core/rpc.py server side, core/gcs.py pubsub/KV fan-out) ---
    "ray_tpu_rpc_server_handler_seconds": (
        HISTOGRAM, "Server-side handler execution time of inbound RPC "
        "calls (handler start to handler return), per method.",
        ("method",), LATENCY_BOUNDARIES),
    "ray_tpu_rpc_server_queue_wait_seconds": (
        HISTOGRAM, "Server-side queue wait of inbound RPC calls (frame "
        "read to handler start — event-loop backlog), per method.",
        ("method",), LATENCY_BOUNDARIES),
    "ray_tpu_rpc_server_calls_total": (
        COUNTER, "Inbound RPC calls dispatched server-side, per method "
        "and caller kind (worker / agent / driver / head / peer).",
        ("method", "caller"), None),
    "ray_tpu_rpc_server_errors_total": (
        COUNTER, "Inbound RPC calls whose handler raised, per method.",
        ("method",), None),
    # Per-process loop-lag histogram: the Python analog of Ray's asio
    # event-loop stats. A self-scheduling callback measures scheduled-
    # vs-actual delay; sustained lag means the loop is starved.
    "ray_tpu_event_loop_lag_seconds": (
        HISTOGRAM, "Scheduled-vs-actual delay of a self-scheduling "
        "probe callback on each process event loop (head / agent / "
        "worker / driver).", ("proc",), LATENCY_BOUNDARIES),
    "ray_tpu_pubsub_messages_total": (
        COUNTER, "Pubsub notifications fanned out by the head, per "
        "channel (one per subscriber per publish).",
        ("channel",), None),
    "ray_tpu_pubsub_bytes_total": (
        COUNTER, "Approximate payload bytes fanned out by head pubsub, "
        "per channel (payload size x live subscribers).",
        ("channel",), None),
    "ray_tpu_pubsub_fanout": (
        GAUGE, "Live subscriber count per pubsub channel (the fan-out "
        "factor every publish pays).", ("channel",), None),
    "ray_tpu_pubsub_dead_subscribers_pruned_total": (
        COUNTER, "Dead subscriber connections pruned from pubsub "
        "channels (connection loss / worker death).", (), None),
    "ray_tpu_kv_write_bytes_total": (
        COUNTER, "Raw value bytes written through h_kv_put, per "
        "namespace.", ("ns",), None),
    "ray_tpu_kv_write_amplified_bytes_total": (
        COUNTER, "Amplified KV write bytes: value bytes x downstream "
        "fan-out (store write + watcher/subscriber deliveries), per "
        "namespace.", ("ns",), None),
    "ray_tpu_metrics_history_series_capped_total": (
        COUNTER, "Series evicted by the per-metric series-count cap "
        "(high-cardinality tag explosion guard).", (), None),
}

_KIND_TO_CLS = {
    COUNTER: _metrics.Counter,
    GAUGE: _metrics.Gauge,
    HISTOGRAM: _metrics.Histogram,
}

_enabled: Optional[bool] = None
_instances: Dict[str, _metrics.Metric] = {}
_instances_lock = threading.Lock()

# Timeline event ring buffer (see module docstring).
_EVENT_CAP = 1000
_events: List[dict] = []
_events_lock = threading.Lock()


_proc_tag: Optional[str] = None
_node_tag: Optional[str] = None


def proc_tag() -> str:
    """This process's identity for per-process gauges."""
    global _proc_tag
    if _proc_tag is None:
        _proc_tag = str(os.getpid())
    return _proc_tag


def node_tag() -> str:
    """This node's identity for per-node gauges (the head process has
    no RAY_TPU_NODE_ID in its environment)."""
    global _node_tag
    if _node_tag is None:
        _node_tag = os.environ.get("RAY_TPU_NODE_ID", "head")[:12]
    return _node_tag


def enabled() -> bool:
    """Cached per-process switch (config ``metrics_enabled`` /
    ``RAY_TPU_METRICS_ENABLED``). Default on: the acceptance bar for the
    runtime is that it is observable out of the box."""
    global _enabled
    if _enabled is None:
        try:
            from ray_tpu.core.config import get_config

            _enabled = bool(get_config().metrics_enabled)
        except Exception:
            _enabled = os.environ.get(
                "RAY_TPU_METRICS_ENABLED", "1").lower() not in (
                    "0", "false", "no")
    return _enabled


def reset_for_testing() -> None:
    """Drop cached state (enabled flag, metric instances, events) AND
    unregister the catalog metrics, so recorded values don't leak into
    the next test — without this, the idempotent registry would hand
    the old instances (old values included) right back."""
    global _enabled
    _enabled = None
    with _instances_lock:
        _instances.clear()
    with _events_lock:
        _events.clear()
    with _metrics._registry_lock:
        for name in CATALOG:
            _metrics._registry.pop(name, None)


def metric(name: str) -> _metrics.Metric:
    """The live instance for a catalog metric, created on first use."""
    m = _instances.get(name)
    if m is not None:
        return m
    with _instances_lock:
        m = _instances.get(name)
        if m is None:
            kind, desc, tag_keys, bounds = CATALOG[name]
            cls = _KIND_TO_CLS[kind]
            if kind == HISTOGRAM:
                m = cls(name, desc, boundaries=bounds, tag_keys=tag_keys)
            else:
                m = cls(name, desc, tag_keys=tag_keys)
            _instances[name] = m
    return m


def ensure_all() -> None:
    """Instantiate every catalog metric (guard test / exposition
    completeness: a scrape shows the full namespace, not just metrics
    that happened to fire)."""
    for name in CATALOG:
        metric(name)


# -- hot-path recorders (each a no-op when the plane is disabled) -------

def inc(name: str, value: float = 1.0,
        tags: Optional[Dict[str, str]] = None) -> None:
    if not enabled():
        return
    try:
        metric(name).inc(value, tags)
    except Exception:
        pass


def set_gauge(name: str, value: float,
              tags: Optional[Dict[str, str]] = None) -> None:
    if not enabled():
        return
    try:
        metric(name).set(value, tags)
    except Exception:
        pass


def observe(name: str, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
    if not enabled():
        return
    try:
        metric(name).observe(value, tags)
    except Exception:
        pass


def flush() -> None:
    _metrics.flush_metrics()


# -- timeline events ----------------------------------------------------

def event(cat: str, name: str, ts: Optional[float] = None,
          dur: Optional[float] = None,
          args: Optional[Dict[str, Any]] = None) -> None:
    """Record one timeline event (chrome-tracing lane ``cat``). ``ts``
    is wall-clock seconds (defaults to now); ``dur`` seconds makes it a
    complete event, None an instant marker."""
    if not enabled():
        return
    ev = {"cat": cat, "name": name,
          "ts": time.time() if ts is None else ts}
    if dur is not None:
        ev["dur"] = dur
    if args:
        ev["args"] = args
    with _events_lock:
        _events.append(ev)
        if len(_events) > _EVENT_CAP:
            del _events[:_EVENT_CAP // 2]


def local_timeline_events() -> List[dict]:
    with _events_lock:
        return [dict(ev) for ev in _events]


def _push_events(cw) -> None:
    """Metrics push hook: ship this process's event buffer to the head
    KV (overwrite — the buffer is the retained window)."""
    with _events_lock:
        if not _events:
            return
        payload = list(_events)
    blob = json.dumps(payload).encode()
    key = f"timeline:{cw.worker_id.hex()}".encode()
    cw.loop_thread.submit(cw.head.call("kv_put", {
        "ns": "timeline", "key": key, "value": blob,
        "overwrite": True,
    }))


_metrics.register_push_hook(_push_events)


def collect_timeline_events() -> List[dict]:
    """Merge every process's pushed timeline events (driver-side)."""
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    if cw is None:
        raise RuntimeError("ray_tpu not initialized")
    keys = cw.loop_thread.run(
        cw.head.call("kv_keys", {"ns": "timeline",
                                 "prefix": b"timeline:"}))
    merged: List[dict] = []
    for key in keys.get("keys", []):
        reply = cw.loop_thread.run(
            cw.head.call("kv_get", {"ns": "timeline", "key": key}))
        blob = reply.get("value")
        if not blob:
            continue
        try:
            merged.extend(json.loads(bytes(blob).decode()))
        except ValueError:
            continue
    merged.sort(key=lambda ev: ev.get("ts", 0.0))
    return merged
