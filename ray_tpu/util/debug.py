"""Cluster debug plane: on-demand dumps and the "why is it stuck"
explainer.

Reference: ``ray stack`` / ``ray debug`` + the state API's summaries
(Ray paper, arXiv:1712.05889 §state). Driver-side veneer over the
head's fan-out handlers:

- ``cluster_debug_dump()`` — every process's flight-recorder ring +
  live all-thread stacks (head, workers, node agents, this driver).
- ``write_debug_bundle(out_dir)`` — a post-mortem bundle: rings,
  stacks, state-API tables, scheduler wait state, a merged metrics
  snapshot and the chrome-tracing timeline.
- ``why(kind, ident)`` — walks the recorded events and live state
  tables to print the causal chain behind a task/actor/object's
  current state (e.g. "PENDING: waiting for resources {'TPU': 4.0}:
  feasible on 0/2 alive node(s)").
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from ray_tpu.util import flight_recorder
from ray_tpu.util.state import _call


def cluster_debug_dump(include_events: bool = True,
                       include_stacks: bool = True,
                       timeout_s: float = 5.0) -> dict:
    """Fan out ``debug_dump`` cluster-wide and splice in this driver
    process's own slice (the head can't dial an in-process driver)."""
    reply = _call("debug_dump_cluster", {
        "include_events": include_events,
        "include_stacks": include_stacks,
        "timeout_s": timeout_s,
    })
    entries = reply.get("entries", [])
    pids = {e.get("pid") for e in entries if e.get("pid")}
    if os.getpid() not in pids:
        local = {
            "source": "driver",
            "pid": os.getpid(),
            "ts": time.time(),
            "stacks": (flight_recorder.dump_stacks()
                       if include_stacks else {}),
        }
        if include_events:
            local["events"] = flight_recorder.snapshot()
        entries.append(local)
    return {"entries": entries, "ts": reply.get("ts", time.time())}


def cluster_stacks(timeout_s: float = 5.0) -> Dict[str, Dict[str, list]]:
    """``{source: {thread: [frame lines]}}`` for every process."""
    dump = cluster_debug_dump(include_events=False, timeout_s=timeout_s)
    out: Dict[str, Dict[str, list]] = {}
    for entry in dump["entries"]:
        key = entry.get("source", "?")
        if entry.get("error"):
            out[key] = {"<error>": [entry["error"]]}
        else:
            out[key] = entry.get("stacks", {})
    return out


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in name)


def _jsonable_metrics(merged: Dict[str, dict]) -> Dict[str, dict]:
    """collect_metrics keys values by tag *tuples*; re-shape for json."""
    out = {}
    for name, data in merged.items():
        row = {k: v for k, v in data.items() if k != "values"}
        row["values"] = [[list(map(list, tk)), v]
                         for tk, v in data["values"].items()]
        out[name] = row
    return out


def write_debug_bundle(out_dir: str, timeout_s: float = 10.0,
                       profile_duration_s: float = 1.0,
                       trace_duration_s: float = 1.0) -> dict:
    """Write a cluster-wide post-mortem bundle and return its manifest.

    Layout: ``rings/<source>.json``, ``stacks/<source>.txt``,
    ``state/{nodes,workers,actors,tasks,objects,placement_groups,
    jobs}.json``, ``sched_state.json``, ``metrics.json``,
    ``timeline.json``, ``history/series.json`` (the head's metrics
    time-series store: the trajectory that LED here, not just the
    endpoint), ``alerts.json`` (firing alerts + recent fire/resolve
    episodes with series evidence), ``rpc/stats.json`` (the
    control-plane load observatory: per-handler RPC accounting,
    top talkers, event-loop lag, pubsub/KV amplification),
    ``profile/`` (a short
    cluster-wide sampling capture: per-source folded stacks +
    flamegraph HTML; ``profile_duration_s=0`` skips it), ``trace/``
    (a short cluster-wide device-trace capture: per-source
    trace.json.gz + parsed op tables + merged host+device timeline;
    ``trace_duration_s=0`` skips it), ``manifest.json``. Sections that
    fail (a dead subsystem is exactly when you need the rest) are
    recorded in the manifest's ``errors`` instead of aborting the
    bundle."""
    os.makedirs(out_dir, exist_ok=True)
    manifest: Dict[str, Any] = {"created": time.time(), "errors": {},
                                "sources": [], "nodes": []}

    dump = cluster_debug_dump(timeout_s=timeout_s)
    rings_dir = os.path.join(out_dir, "rings")
    stacks_dir = os.path.join(out_dir, "stacks")
    os.makedirs(rings_dir, exist_ok=True)
    os.makedirs(stacks_dir, exist_ok=True)
    nodes_seen = set()
    for entry in dump["entries"]:
        source = _sanitize(entry.get("source", "unknown"))
        if entry.get("node_id"):
            nodes_seen.add(entry["node_id"])
        if entry.get("shipped"):
            # A dead process's shipped ring tail: ring evidence only —
            # no live stacks exist for it, so it files under rings/
            # and its own manifest list, not sources.
            manifest.setdefault("shipped", []).append(
                entry.get("source", "unknown"))
            with open(os.path.join(rings_dir, f"{source}.json"),
                      "w") as f:
                json.dump(entry, f, indent=1)
            continue
        manifest["sources"].append(entry.get("source", "unknown"))
        if entry.get("error"):
            manifest["errors"][source] = entry["error"]
            continue
        with open(os.path.join(rings_dir, f"{source}.json"), "w") as f:
            json.dump({k: v for k, v in entry.items() if k != "stacks"},
                      f, indent=1)
        with open(os.path.join(stacks_dir, f"{source}.txt"), "w") as f:
            for thread, frames in (entry.get("stacks") or {}).items():
                f.write(f"--- {thread} ---\n")
                for line in frames:
                    f.write(line + "\n")
                f.write("\n")
    manifest["nodes"] = sorted(nodes_seen)

    state_dir = os.path.join(out_dir, "state")
    os.makedirs(state_dir, exist_ok=True)
    from ray_tpu.util import state as ust

    tables = {
        "nodes": ust.list_nodes,
        "workers": ust.list_workers,
        "actors": ust.list_actors,
        "tasks": lambda: ust.list_tasks(limit=10000),
        "objects": ust.list_objects,
        "placement_groups": ust.list_placement_groups,
        "jobs": ust.list_jobs,
    }
    for name, fn in tables.items():
        try:
            with open(os.path.join(state_dir, f"{name}.json"), "w") as f:
                json.dump(fn(), f, indent=1, default=str)
        except Exception as e:  # noqa: BLE001 — partial bundles are fine
            manifest["errors"][f"state/{name}"] = f"{type(e).__name__}: {e}"

    for name, producer in (
        ("sched_state.json", lambda: _call("debug_sched_state")),
        ("metrics.json", _collect_metrics_json),
        ("timeline.json", _timeline_json),
    ):
        try:
            with open(os.path.join(out_dir, name), "w") as f:
                json.dump(producer(), f, indent=1, default=str)
        except Exception as e:  # noqa: BLE001
            manifest["errors"][name] = f"{type(e).__name__}: {e}"

    try:
        hist = _call("metrics_history_snapshot", {"max_points": 512})
        if hist.get("enabled"):
            hist_dir = os.path.join(out_dir, "history")
            os.makedirs(hist_dir, exist_ok=True)
            with open(os.path.join(hist_dir, "series.json"), "w") as f:
                json.dump(hist, f, indent=1, default=str)
            manifest["history"] = {
                "series": hist.get("series_count", 0),
                "points": hist.get("point_count", 0),
                "bytes": hist.get("bytes", 0),
                "evictions": hist.get("evictions", 0),
            }
    except Exception as e:  # noqa: BLE001
        manifest["errors"]["history"] = f"{type(e).__name__}: {e}"

    try:
        alerts = _call("alerts")
        with open(os.path.join(out_dir, "alerts.json"), "w") as f:
            json.dump(alerts, f, indent=1, default=str)
        manifest["alerts"] = {
            "enabled": alerts.get("enabled", False),
            "firing": len(alerts.get("firing", [])),
            "episodes": len(alerts.get("episodes", [])),
        }
    except Exception as e:  # noqa: BLE001
        manifest["errors"]["alerts"] = f"{type(e).__name__}: {e}"

    try:
        rpc = _call("rpc_stats", {"top": 50})
        rpc_dir = os.path.join(out_dir, "rpc")
        os.makedirs(rpc_dir, exist_ok=True)
        with open(os.path.join(rpc_dir, "stats.json"), "w") as f:
            json.dump(rpc, f, indent=1, default=str)
        manifest["rpc"] = {
            "methods": len(rpc.get("methods", [])),
            "talkers": len(rpc.get("talkers", [])),
            "loops": len(rpc.get("loops", [])),
            "pruned_subscribers": rpc.get("amplification", {})
            .get("pruned_total", 0),
        }
    except Exception as e:  # noqa: BLE001
        manifest["errors"]["rpc"] = f"{type(e).__name__}: {e}"

    if profile_duration_s and profile_duration_s > 0:
        # A short sampling window across every process: "what was
        # everyone DOING" alongside the point-in-time stacks.
        try:
            from ray_tpu.util import profiler

            reply = profiler.capture_cluster(
                "all", duration_s=profile_duration_s, hz=50.0)
            prof = profiler.write_profile_outputs(
                reply, os.path.join(out_dir, "profile"),
                title="debug bundle profile")
            manifest["profile"] = {
                "sources": prof["sources"],
                "samples": prof["samples"],
                "unreachable": prof["errors"],
            }
        except Exception as e:  # noqa: BLE001
            manifest["errors"]["profile"] = f"{type(e).__name__}: {e}"

    if trace_duration_s and trace_duration_s > 0:
        # A short device-trace window across every process: which XLA
        # ops were running, per train step, alongside the host samples.
        try:
            from ray_tpu.util import device_trace

            reply = device_trace.capture_cluster(
                "all", duration_s=trace_duration_s)
            tr = device_trace.write_trace_outputs(
                reply, os.path.join(out_dir, "trace"),
                title="debug bundle device trace")
            manifest["trace"] = {
                "sources": tr["sources"],
                "device_events": tr["device_events"],
                "steps": len(tr["steps"]),
                "unreachable": tr["errors"],
            }
        except Exception as e:  # noqa: BLE001
            manifest["errors"]["trace"] = f"{type(e).__name__}: {e}"

    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


def _collect_metrics_json():
    from ray_tpu.util import metrics as um

    return _jsonable_metrics(um.collect_metrics())


def _timeline_json():
    from ray_tpu.util.timeline import timeline

    return timeline(None)


# ---------------------------------------------------------------------------
# the "why is it stuck" explainer
# ---------------------------------------------------------------------------

def why(kind: str, ident: str, timeout_s: float = 5.0) -> str:
    """Explain a task/actor/object/placement-group's current state
    causally. ``ident`` is a full or prefix hex id (objects need the
    full hex to consult the directory). One cluster-wide ring fetch
    serves every evidence trail the explanation needs (including the
    object→task recursion)."""
    kind = kind.lower()
    ident = ident.lower()
    try:
        dump = cluster_debug_dump(include_stacks=False,
                                  timeout_s=timeout_s)
    except Exception:
        dump = {"entries": []}
    if kind == "task":
        return "\n".join(_why_task(ident, dump))
    if kind == "actor":
        return "\n".join(_why_actor(ident, dump))
    if kind == "object":
        return "\n".join(_why_object(ident, dump))
    if kind in ("placement-group", "placement_group", "pg"):
        return "\n".join(_why_pg(ident, dump))
    raise ValueError(
        f"unknown kind {kind!r} (task|actor|object|placement-group)")


def _matching_flight_events(tag_key: str, ident: str, dump: dict,
                            limit: int = 12) -> List[str]:
    """Recorded events from an already-fetched cluster dump whose
    ``tag_key`` tag matches the id prefix — the flight recorder is the
    causal evidence trail."""
    rows = []
    for entry in dump["entries"]:
        for ev in entry.get("events") or []:
            tags = ev.get("tags") or {}
            value = str(tags.get(tag_key, ""))
            # Tags hold truncated ids; match on either being a prefix
            # of the other.
            if value and (value.startswith(ident)
                          or ident.startswith(value)):
                rows.append((ev["ts"], entry.get("source", "?"), ev))
    rows.sort(key=lambda r: r[0])
    out = []
    for ts, source, ev in rows[-limit:]:
        tags = ev.get("tags") or {}
        detail = ", ".join(f"{k}={v}" for k, v in tags.items()
                           if k != tag_key)
        stamp = time.strftime("%H:%M:%S", time.localtime(ts))
        out.append(f"  [{stamp}] {source}: {ev['subsystem']}/"
                   f"{ev['event']} ({detail})" if detail else
                   f"  [{stamp}] {source}: {ev['subsystem']}/"
                   f"{ev['event']}")
    return out


def _cluster_availability_line(sched: dict) -> str:
    parts = []
    for n in sched.get("nodes", []):
        parts.append(f"{n['node_id'][:8]}[{n['state']}] "
                     f"avail={n.get('available', {})}")
    return "; ".join(parts)


def _why_task(ident: str, dump: dict) -> List[str]:
    lines: List[str] = []
    sched = _call("debug_sched_state")
    pend = [p for p in sched.get("pending", [])
            if p["task_id"].startswith(ident)]
    from ray_tpu.util import state as ust

    events = [e for e in ust.list_task_events(limit=100000)
              if e["task_id"].startswith(ident)]
    if pend:
        p = pend[0]
        what = ("actor creation" if p["is_actor_creation"] else "task")
        lines.append(f"{what} {p['name'] or p['task_id'][:16]} is "
                     f"PENDING (queued {p['age_s']:.1f}s)")
        lines.append(f"  last scheduler decision: "
                     f"{p['wait_reason'] or 'not yet evaluated'}")
        lines.append(f"  requested resources: {p['resources']} "
                     f"(strategy: {p['strategy']})")
        lines.append(f"  cluster: {_cluster_availability_line(sched)}")
        for pg in sched.get("pgs", []):
            if pg["pg_id"][:8] in (p["wait_reason"] or ""):
                lines.append(
                    f"  placement group {pg['pg_id'][:8]}: "
                    f"{pg['state']}, {pg['bundles_placed']}/"
                    f"{pg['bundles']} bundles placed "
                    f"({pg['strategy']})")
    elif events:
        last = events[-1]
        state = last["state"]
        age = time.time() - last["ts"]
        lines.append(f"task {last.get('name') or last['task_id'][:16]} "
                     f"is {state} (for {age:.1f}s)")
        if state == "RUNNING":
            lines.append(f"  executing on worker "
                         f"{(last.get('worker_id') or '?')[:12]} — "
                         f"`ray_tpu debug stacks` shows its frames")
        elif state == "PENDING_EXECUTION":
            lines.append(f"  queued on leased worker "
                         f"{(last.get('worker_id') or '?')[:12]}, "
                         f"waiting for the executor")
        elif state == "FAILED":
            lines.append("  terminal failure — the error object holds "
                         "the traceback (get() raises it)")
    else:
        lines.append(f"no records for task id {ident!r}: it never "
                     "reached the scheduler or event store (wrong id, "
                     "or events already rotated out)")
    trail = _matching_flight_events("task", ident, dump)
    if trail:
        lines.append("recorded events:")
        lines.extend(trail)
    return lines


def _why_actor(ident: str, dump: dict) -> List[str]:
    from ray_tpu.util import state as ust

    lines: List[str] = []
    actors = [a for a in ust.list_actors()
              if a["actor_id"].startswith(ident)]
    if not actors:
        return [f"no actor with id prefix {ident!r}"]
    a = actors[0]
    name = a.get("name") or a.get("class_name") or a["actor_id"][:16]
    lines.append(f"actor {name} is {a['state']} "
                 f"(restarts: {a['num_restarts']}/"
                 f"{a['max_restarts'] if a['max_restarts'] >= 0 else '∞'})")
    if a["state"] in ("PENDING", "RESTARTING"):
        sched = _call("debug_sched_state")
        creations = [p for p in sched.get("pending", [])
                     if p.get("actor_id")
                     and p["actor_id"].startswith(a["actor_id"][:16])]
        if creations:
            p = creations[0]
            lines.append(f"  creation lease pending "
                         f"{p['age_s']:.1f}s: "
                         f"{p['wait_reason'] or 'not yet evaluated'}")
            lines.append(f"  requested resources: {p['resources']}")
            lines.append(f"  cluster: {_cluster_availability_line(sched)}")
        else:
            lines.append("  creation in flight (worker leased, "
                         "constructor running or being pushed)")
        if a["state"] == "RESTARTING" and a.get("death_cause"):
            lines.append(f"  last death: {a['death_cause']}")
    elif a["state"] == "DEAD":
        lines.append(f"  death cause: {a.get('death_cause') or 'unknown'}")
    elif a["state"] == "ALIVE" and a.get("address"):
        lines.append(f"  running on worker {a['address'][2][:12]} "
                     f"at {a['address'][0]}:{a['address'][1]}")
    trail = _matching_flight_events("actor", ident, dump)
    if trail:
        lines.append("recorded events:")
        lines.extend(trail)
    return lines


def _mentioning_flight_events(needle: str, dump: dict,
                              limit: int = 12) -> List[str]:
    """Recorded events whose tag VALUES mention an id prefix anywhere —
    PG involvement usually rides inside wait-reason / message text
    rather than a dedicated tag."""
    rows = []
    for entry in dump["entries"]:
        for ev in entry.get("events") or []:
            tags = ev.get("tags") or {}
            if any(needle in str(v) for v in tags.values()):
                rows.append((ev["ts"], entry.get("source", "?"), ev))
    rows.sort(key=lambda r: r[0])
    out = []
    for ts, source, ev in rows[-limit:]:
        detail = ", ".join(f"{k}={v}"
                           for k, v in (ev.get("tags") or {}).items())
        stamp = time.strftime("%H:%M:%S", time.localtime(ts))
        out.append(f"  [{stamp}] {source}: {ev['subsystem']}/"
                   f"{ev['event']}" + (f" ({detail})" if detail else ""))
    return out


def _why_pg(ident: str, dump: dict) -> List[str]:
    """Walk a placement group's bundle placement + the leases waiting
    on it + recorded lease_infeasible/lease_wait evidence."""
    lines: List[str] = []
    sched = _call("debug_sched_state")
    pgs = [pg for pg in sched.get("pgs", [])
           if pg["pg_id"].startswith(ident)]
    if not pgs:
        return [f"no placement group with id prefix {ident!r}"]
    pg = pgs[0]
    pg_hex = pg["pg_id"]
    name = pg.get("name") or pg_hex[:16]
    lines.append(f"placement group {name} is {pg['state']} "
                 f"({pg['bundles_placed']}/{pg['bundles']} bundles "
                 f"placed, strategy {pg['strategy']})")
    if pg["bundles_placed"] < pg["bundles"]:
        lines.append(f"  {pg['bundles'] - pg['bundles_placed']} "
                     "bundle(s) unplaced — cluster capacity below the "
                     "gang's demand or fragmented across nodes")
        lines.append(f"  cluster: {_cluster_availability_line(sched)}")
    # Leases parked against (or waiting for) THIS PG: involvement shows
    # up in the scheduler's wait-reason text (the sched-state rows
    # carry only the strategy type name, not the PG id, so a bare
    # strategy match would drag in other PGs' leases).
    waiting = [p for p in sched.get("pending", [])
               if pg_hex[:8] in (p.get("wait_reason") or "")]
    for p in waiting:
        what = "actor creation" if p["is_actor_creation"] else "task"
        lines.append(f"  pending {what} {p['name'] or p['task_id'][:16]}"
                     f" (queued {p['age_s']:.1f}s): "
                     f"{p['wait_reason'] or 'not yet evaluated'}")
    trail = (_matching_flight_events("pg", pg_hex[:8], dump)
             + _mentioning_flight_events(pg_hex[:8], dump))
    if trail:
        lines.append("recorded events:")
        lines.extend(trail)
    return lines


def _why_object(ident: str, dump: dict) -> List[str]:
    lines: List[str] = []
    reply = None
    try:
        reply = _call("locate_object", {"object_id": ident})
    except Exception:
        pass
    if reply and reply.get("found"):
        nodes = reply.get("nodes", [])
        lines.append(f"object {ident[:16]} is SEALED "
                     f"({reply.get('size', 0)} bytes) with "
                     f"{len(nodes)} copy/copies")
        for n in nodes:
            lines.append(f"  copy on node {n[:12]}")
        if not reply.get("locations"):
            lines.append("  no reachable holder right now — a get() "
                         "would wait on pull/recovery")
    else:
        lines.append(f"object {ident[:16]} is NOT sealed in the "
                     "cluster store")
        # Causal walk: an unsealed object is produced by its task.
        try:
            from ray_tpu.core.ids import ObjectID

            task_hex = ObjectID.from_hex(ident).task_id().hex()
            lines.append(f"  producing task {task_hex[:16]}:")
            lines.extend("  " + ln for ln in _why_task(task_hex, dump))
        except Exception:
            lines.append("  (id is not a full object hex; cannot derive "
                         "the producing task)")
    trail = _matching_flight_events("object", ident, dump)
    if trail:
        lines.append("recorded events:")
        lines.extend(trail)
    return lines
