"""joblib backend over the cluster (reference: python/ray/util/joblib —
register_ray() lets sklearn's n_jobs parallelism run on the cluster)."""

from __future__ import annotations


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib import register_parallel_backend
    from joblib._parallel_backends import MultiprocessingBackend

    from ray_tpu.util.multiprocessing import Pool

    class RayTpuBackend(MultiprocessingBackend):
        supports_sharedmem = False

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if not ray_tpu.is_initialized():
                ray_tpu.init()
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            if n_jobs is None or n_jobs < 0:
                return max(1, cpus - 1)
            return min(n_jobs, cpus)

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **kwargs):
            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

    register_parallel_backend("ray_tpu", RayTpuBackend)
