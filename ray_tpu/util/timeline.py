"""Task timeline export in chrome://tracing format.

Reference: `ray timeline` (_private/state.py:434 chrome_tracing_dump) —
task state transitions from the event store become complete events
("ph": "X") grouped by worker, loadable in chrome://tracing / Perfetto.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ray_tpu.util.state import list_task_events


def timeline(filename: Optional[str] = None) -> List[dict]:
    events = list_task_events(limit=100000)
    # Pair RUNNING -> FINISHED/FAILED per task.
    start_ts = {}
    trace: List[dict] = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            start_ts[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in start_ts:
            begin = start_ts.pop(tid)
            trace.append({
                "name": ev.get("name") or tid[:8],
                "cat": ev.get("type", "task"),
                "ph": "X",
                "ts": begin["ts"] * 1e6,
                "dur": max(0.0, (ev["ts"] - begin["ts"]) * 1e6),
                "pid": "ray_tpu",
                "tid": ev.get("worker_id", "?")[:12],
                "args": {"task_id": tid,
                         "state": ev["state"]},
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
