"""Task timeline export in chrome://tracing format.

Reference: `ray timeline` (_private/state.py:434 chrome_tracing_dump) —
task state transitions from the event store become complete events
("ph": "X") grouped by worker, loadable in chrome://tracing / Perfetto.

Beyond task events, the export merges the telemetry event stream
(util/telemetry.py) into extra lanes: object transfers (pulls, spills,
restores), retries, circuit-breaker trips, and the train-plane health
lanes (heartbeat misses, hang/death attributions, gang aborts, elastic
resizes) each get their own track, so a fault-injection soak reads as
one coherent picture. The local flight-recorder ring
(util/flight_recorder.py) is merged the same way under ``fr:<subsystem>``
lanes — scheduler wait reasons and node-state transitions land next to
the task lanes they explain.

Two more lane families ride the telemetry stream: ``profile:<pid>``
(continuous-sampler snapshot windows from util/profiler.py — each
window is a complete event whose name is the hottest stack leaf) and
``train/step:r<rank>`` (the gang monitor's per-rank device
step-counter heartbeats: one marker per step/phase change, so a rank
wedged in compile reads differently from one stuck in its jitted
step). When no cluster is attached (or nothing pushed yet), the export
falls back to this process's local telemetry buffer so driver-side
lanes still render.
"""

from __future__ import annotations

import json
from typing import List, Optional


def _task_trace_events(events: List[dict]) -> List[dict]:
    # Pair RUNNING -> FINISHED/FAILED per task.
    start_ts = {}
    trace: List[dict] = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            start_ts[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in start_ts:
            begin = start_ts.pop(tid)
            trace.append({
                "name": ev.get("name") or tid[:8],
                "cat": ev.get("type", "task"),
                "ph": "X",
                "ts": begin["ts"] * 1e6,
                "dur": max(0.0, (ev["ts"] - begin["ts"]) * 1e6),
                "pid": "ray_tpu",
                "tid": ev.get("worker_id", "?")[:12],
                "args": {"task_id": tid,
                         "state": ev["state"]},
            })
    # Still-RUNNING tasks appear as open "B" begin events: a hung task
    # must be visible in the timeline, not silently dropped.
    for tid, begin in start_ts.items():
        trace.append({
            "name": begin.get("name") or tid[:8],
            "cat": begin.get("type", "task"),
            "ph": "B",
            "ts": begin["ts"] * 1e6,
            "pid": "ray_tpu",
            "tid": begin.get("worker_id", "?")[:12],
            "args": {"task_id": tid, "state": "RUNNING"},
        })
    return trace


def telemetry_trace_events(events: List[dict]) -> List[dict]:
    """Convert telemetry events (util/telemetry.py ``event()`` dicts)
    into chrome-tracing events, one lane (tid) per category."""
    trace: List[dict] = []
    for ev in events:
        cat = ev.get("cat", "event")
        out = {
            "name": ev.get("name", "?"),
            "cat": cat,
            "ts": float(ev.get("ts", 0.0)) * 1e6,
            "pid": "ray_tpu",
            "tid": cat,
            "args": ev.get("args") or {},
        }
        dur = ev.get("dur")
        if dur is not None:
            out["ph"] = "X"
            out["dur"] = max(0.0, float(dur) * 1e6)
        else:
            out["ph"] = "i"
            out["s"] = "p"
        trace.append(out)
    return trace


def flight_trace_events(events: List[dict]) -> List[dict]:
    """Convert flight-recorder snapshot rows into chrome-tracing
    instant events, one lane per subsystem (``fr:sched``, ``fr:gcs``,
    ...)."""
    trace: List[dict] = []
    for ev in events:
        subsystem = ev.get("subsystem", "?")
        trace.append({
            "name": ev.get("event", "?"),
            "cat": f"fr:{subsystem}",
            "ts": float(ev.get("ts", 0.0)) * 1e6,
            "ph": "i",
            "s": "p",
            "pid": "ray_tpu",
            "tid": f"fr:{subsystem}",
            "args": dict(ev.get("tags") or {},
                         severity=ev.get("severity", "info")),
        })
    return trace


def alert_trace_events(episodes: List[dict]) -> List[dict]:
    """Convert alert-engine episodes (util/alerts.py) into an
    ``alerts`` lane next to the ``fr:``/``profile:`` lanes: resolved
    episodes render as complete fire→resolve spans, still-firing ones
    as instant fire markers (an open alert must be visible, not
    dropped)."""
    trace: List[dict] = []
    for ep in episodes:
        fired = float(ep.get("fired_ts") or 0.0)
        resolved = ep.get("resolved_ts")
        args = {
            "rule": ep.get("rule", "?"),
            "metric": ep.get("metric", ""),
            "series": ",".join(f"{k}={v}" for k, v in
                               sorted((ep.get("tags") or {}).items())),
            "value": ep.get("value"),
            "threshold": ep.get("threshold"),
            "severity": ep.get("severity", "warn"),
        }
        out = {
            "name": ep.get("rule", "?"),
            "cat": "alerts",
            "ts": fired * 1e6,
            "pid": "ray_tpu",
            "tid": "alerts",
            "args": args,
        }
        if resolved:
            out["ph"] = "X"
            out["dur"] = max(0.0, (float(resolved) - fired) * 1e6)
        else:
            out["ph"] = "i"
            out["s"] = "p"
        trace.append(out)
    return trace


def timeline(filename: Optional[str] = None,
             events: Optional[List[dict]] = None,
             include_telemetry: bool = True,
             include_flight: bool = True,
             include_alerts: bool = True) -> List[dict]:
    if events is None:
        from ray_tpu.util.state import list_task_events

        events = list_task_events(limit=100000)
    trace = _task_trace_events(events)
    if include_telemetry:
        try:
            from ray_tpu.util import telemetry

            try:
                merged = telemetry.collect_timeline_events()
            except Exception:
                # No cluster attached: this process's own buffer still
                # carries its lanes (profile:<pid>, train/step:r<rank>,
                # retries) — a driver-side export must not lose them.
                merged = telemetry.local_timeline_events()
            trace.extend(telemetry_trace_events(merged))
        except Exception:
            pass  # telemetry plane disabled entirely
    if include_flight:
        try:
            from ray_tpu.util import flight_recorder

            trace.extend(flight_trace_events(flight_recorder.snapshot()))
        except Exception:
            pass
    if include_alerts:
        try:
            from ray_tpu.util.state import _call

            reply = _call("alerts")
            trace.extend(alert_trace_events(reply.get("episodes", [])))
        except Exception:  # lint: allow-silent(no cluster attached / engine disabled — lane is optional)
            pass
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
