"""multiprocessing.Pool drop-in over the task substrate.

Reference: python/ray/util/multiprocessing/ — a Pool whose workers are
cluster tasks/actors, so existing multiprocessing code scales past one
machine unchanged.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


class _PoolWorker:
    def run(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))

    def run_batch(self, fn, chunk):
        return [fn(item) for item in chunk]

    def starrun_batch(self, fn, chunk):
        return [fn(*item) for item in chunk]


class AsyncResult:
    def __init__(self, refs: List[Any], flatten: bool = False,
                 single: bool = False):
        self._refs = refs
        self._flatten = flatten
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        if self._single:
            return out[0]
        if self._flatten:
            return [x for chunk in out for x in chunk]
        return out

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            self.get(timeout=1.0)
            return True
        except Exception:
            return False


class Pool:
    """Reference: ray.util.multiprocessing.Pool."""

    def __init__(self, processes: Optional[int] = None, *,
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(
                ray_tpu.cluster_resources().get("CPU", 2)) - 1)
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        self._actors = [
            ray_tpu.remote(_PoolWorker).options(**opts).remote()
            for _ in range(processes)]
        self._pool = ActorPool(self._actors)
        self._closed = False
        self._rr = itertools.cycle(range(processes))
        self._outstanding: List[Any] = []

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    @staticmethod
    def _chunks(iterable, chunksize) -> List[list]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // 64 or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    # -- apply ----------------------------------------------------------
    def apply(self, fn: Callable, args: tuple = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get(timeout=None)

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict = None, callback: Callable = None,
                    error_callback: Callable = None) -> AsyncResult:
        self._check()
        actor = self._actors[next(self._rr)]
        ref = actor.run.remote(fn, args, kwds)
        self._outstanding.append(ref)
        if callback is not None or error_callback is not None:
            def fire(fut):
                try:
                    value = fut.result()
                except Exception as e:
                    if error_callback is not None:
                        error_callback(e)
                    return
                if callback is not None:
                    callback(value)

            ref.future().add_done_callback(fire)
        return AsyncResult([ref], single=True)

    # -- map ------------------------------------------------------------
    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get(timeout=None)

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> AsyncResult:
        self._check()
        refs = []
        for i, chunk in enumerate(self._chunks(iterable, chunksize)):
            actor = self._actors[i % len(self._actors)]
            refs.append(actor.run_batch.remote(fn, chunk))
        self._outstanding.extend(refs)
        return AsyncResult(refs, flatten=True)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        self._check()
        refs = []
        for i, chunk in enumerate(self._chunks(iterable, chunksize)):
            actor = self._actors[i % len(self._actors)]
            refs.append(actor.starrun_batch.remote(fn, chunk))
        return AsyncResult(refs, flatten=True).get(timeout=None)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check()
        refs = []
        for i, chunk in enumerate(self._chunks(iterable, chunksize)):
            actor = self._actors[i % len(self._actors)]
            refs.append(actor.run_batch.remote(fn, chunk))
        for ref in refs:
            yield from ray_tpu.get(ref, timeout=None)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check()
        chunks = self._chunks(iterable, chunksize)
        for result in self._pool.map_unordered(
                lambda a, c: a.run_batch.remote(fn, c), chunks):
            yield from result

    # -- lifecycle -------------------------------------------------------
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass

    def join(self):
        """Wait for outstanding work, then release the worker actors —
        the standard close()+join() lifecycle must not leak actors."""
        if not self._closed:
            raise ValueError("join() before close()")
        if self._outstanding:
            ray_tpu.wait(self._outstanding,
                         num_returns=len(self._outstanding), timeout=None)
            self._outstanding = []
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
