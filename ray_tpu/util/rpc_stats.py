"""Control-plane load observatory: server-side RPC accounting, event-
loop lag probes, and pubsub/KV fan-out amplification stats.

Reference: Ray instruments exactly this layer — per-handler gRPC server
metrics plus asio event-loop stats (src/ray/common/asio/) — because a
centralized GCS is the scaling bottleneck by construction
(arXiv:1712.05889). This module is the Python analog, shared by every
process:

- :class:`ServerStats` — a bounded in-process table of inbound-call
  accounting keyed per handler and per (handler x caller-kind): call
  counts, queue wait (frame read -> handler start), handler time,
  payload/reply bytes, errors. ``core/rpc.py`` records every dispatched
  frame here; the head's ``HeadClient`` local path records the
  in-process driver calls that never cross a socket. The talker table
  has a HARD entry cap — overflow folds into one ``__other__`` row, so
  cardinality cannot grow without bound (and nothing per-caller is ever
  pushed through the KV metrics plane; only the bounded per-method
  histograms are).
- :class:`LoopLagProbe` — a self-scheduling callback on an event loop
  that measures scheduled-vs-actual delay into the
  ``ray_tpu_event_loop_lag_seconds`` histogram (tagged per process +
  loop), so "the head stalled" becomes a per-process, per-window fact.
  Lag past the stall threshold leaves an ``rpc/loop_stall`` flight
  event as the evidence trail.
- :class:`AmplificationStats` — head-side per-channel pubsub fan-out
  (messages/bytes out, dead-subscriber drops) and per-namespace KV
  write amplification (value bytes x downstream fan-out).

Hot-path contract: ``ServerStats.record`` is a dict upsert under one
lock plus (when the metrics plane is on) two histogram observes and a
counter inc; everything imports telemetry lazily so bootstrap order is
unchanged, and every snapshot/summary path is JSONable for the
``rpc_stats`` head handler, the hotrpc CLI, ``GET /rpc``, and the debug
bundle ``rpc/`` section.
"""

from __future__ import annotations

import asyncio
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: Hard cap on distinct (method, caller) talker rows per process.
DEFAULT_ENTRY_CAP = 512
#: Overflow fold key once the talker cap is hit.
OVERFLOW_KEY = ("__other__", "__other__")

#: Known caller kinds (anything else folds to "peer").
CALLER_KINDS = ("worker", "agent", "driver", "head", "peer")


def _boundaries() -> List[float]:
    from ray_tpu.util.telemetry import LATENCY_BOUNDARIES

    return LATENCY_BOUNDARIES


def caller_kind(conn: Any) -> str:
    """Classify the far side of a connection for accounting.

    Registration handlers stamp ``conn.state["caller_kind"]`` (worker /
    agent / driver); before registration — or on connections that never
    register, like a worker's own link *to* the head — fall back to the
    connection name (dialed head links are named ``*-head``)."""
    state = getattr(conn, "state", None)
    if isinstance(state, dict):
        kind = state.get("caller_kind")
        if kind:
            return kind
    name = getattr(conn, "name", "") or ""
    if "head" in name:
        return "head"
    return "peer"


class _MethodRow:
    __slots__ = ("calls", "errors", "queue_s", "queue_max", "handler_s",
                 "handler_max", "recv_bytes", "reply_bytes",
                 "handler_hist", "queue_hist")

    def __init__(self, nbuckets: int):
        self.calls = 0
        self.errors = 0
        self.queue_s = 0.0
        self.queue_max = 0.0
        self.handler_s = 0.0
        self.handler_max = 0.0
        self.recv_bytes = 0
        self.reply_bytes = 0
        # len(boundaries)+1 buckets, last = +Inf (matches the telemetry
        # histogram layout so percentiles agree across surfaces).
        self.handler_hist = [0] * nbuckets
        self.queue_hist = [0] * nbuckets


class ServerStats:
    """Bounded per-process inbound-RPC accounting table."""

    def __init__(self, entry_cap: int = DEFAULT_ENTRY_CAP):
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock("rpc_stats.ServerStats._lock")
        self.entry_cap = int(entry_cap)
        self.started_at = time.time()
        self._bounds = list(_boundaries())
        self._nbuckets = len(self._bounds) + 1
        #: method -> _MethodRow (methods are code-bounded, no cap needed).
        self._methods: Dict[str, _MethodRow] = {}
        #: (method, caller) -> [calls, handler_s, recv_bytes] — capped.
        self._talkers: Dict[Tuple[str, str], list] = {}
        self.overflow = 0

    def _bucket(self, v: float) -> int:
        for i, b in enumerate(self._bounds):
            if v <= b:
                return i
        return self._nbuckets - 1

    def register_methods(self, names) -> None:
        """Preregister handler names so the accounting table covers the
        full dispatch dict even before traffic (parity guarantee: a
        newly added ``h_*`` cannot dodge instrumentation)."""
        with self._lock:
            for name in names:
                if name not in self._methods:
                    self._methods[name] = _MethodRow(self._nbuckets)

    def methods(self) -> List[str]:
        with self._lock:
            return sorted(self._methods)

    def record(self, method: str, caller: str, queue_wait_s: float,
               handler_s: float, recv_bytes: int = 0,
               reply_bytes: int = 0, ok: bool = True) -> None:
        with self._lock:
            row = self._methods.get(method)
            if row is None:
                row = self._methods[method] = _MethodRow(self._nbuckets)
            row.calls += 1
            if not ok:
                row.errors += 1
            row.queue_s += queue_wait_s
            if queue_wait_s > row.queue_max:
                row.queue_max = queue_wait_s
            row.handler_s += handler_s
            if handler_s > row.handler_max:
                row.handler_max = handler_s
            row.recv_bytes += recv_bytes
            row.reply_bytes += reply_bytes
            row.handler_hist[self._bucket(handler_s)] += 1
            row.queue_hist[self._bucket(queue_wait_s)] += 1
            key = (method, caller)
            talker = self._talkers.get(key)
            if talker is None:
                if len(self._talkers) >= self.entry_cap:
                    self.overflow += 1
                    key = OVERFLOW_KEY
                    talker = self._talkers.get(key)
                if talker is None:
                    talker = self._talkers[key] = [0, 0.0, 0]
            talker[0] += 1
            talker[1] += handler_s
            talker[2] += recv_bytes
        from ray_tpu.util import telemetry

        telemetry.observe("ray_tpu_rpc_server_handler_seconds",
                          handler_s, {"method": method})
        telemetry.observe("ray_tpu_rpc_server_queue_wait_seconds",
                          queue_wait_s, {"method": method})
        telemetry.inc("ray_tpu_rpc_server_calls_total", 1,
                      {"method": method, "caller": caller})
        if not ok:
            telemetry.inc("ray_tpu_rpc_server_errors_total", 1,
                          {"method": method})

    def snapshot(self, top: int = 0) -> dict:
        """JSONable accounting snapshot: per-method rows (with p50/p99
        from the in-process buckets) plus the top-talkers table."""
        from ray_tpu.util.metrics_history import _bucket_percentile

        with self._lock:
            methods = []
            for name, r in self._methods.items():
                hist = [float(c) for c in r.handler_hist]
                qist = [float(c) for c in r.queue_hist]
                methods.append({
                    "method": name,
                    "calls": r.calls,
                    "errors": r.errors,
                    "handler_s": round(r.handler_s, 6),
                    "handler_max_s": round(r.handler_max, 6),
                    "handler_p50_s": _bucket_percentile(
                        self._bounds, hist, 0.50),
                    "handler_p99_s": _bucket_percentile(
                        self._bounds, hist, 0.99),
                    "queue_wait_s": round(r.queue_s, 6),
                    "queue_wait_max_s": round(r.queue_max, 6),
                    "queue_wait_p99_s": _bucket_percentile(
                        self._bounds, qist, 0.99),
                    "recv_bytes": r.recv_bytes,
                    "reply_bytes": r.reply_bytes,
                })
            talkers = [
                {"method": m, "caller": c, "calls": t[0],
                 "handler_s": round(t[1], 6), "recv_bytes": t[2]}
                for (m, c), t in self._talkers.items()]
            overflow = self.overflow
        methods.sort(key=lambda r: (-r["handler_s"], r["method"]))
        talkers.sort(key=lambda r: (-r["calls"], r["method"]))
        if top:
            talkers = talkers[:top]
        return {
            "proc": f"{os.getpid()}",
            "since_s": round(time.time() - self.started_at, 3),
            "entry_cap": self.entry_cap,
            "overflow": overflow,
            "methods": methods,
            "talkers": talkers,
        }


class LoopLagProbe:
    """Self-scheduling event-loop lag probe (asio-stats analog).

    ``call_later(interval)`` records ``actual - scheduled`` each tick:
    a healthy loop shows sub-millisecond lag; a loop starved by a
    blocking handler shows the block's full duration on the next tick.
    """

    def __init__(self, loop: asyncio.AbstractEventLoop, name: str,
                 interval_s: float = 0.25,
                 stall_threshold_s: float = 0.5):
        self.loop = loop
        self.name = name
        self.interval_s = float(interval_s)
        self.stall_threshold_s = float(stall_threshold_s)
        self.tag = f"{os.getpid()}/{name}"
        self._stopped = False
        self._handle: Optional[asyncio.TimerHandle] = None
        self._expected = 0.0
        self._bounds = list(_boundaries())
        self._hist = [0] * (len(self._bounds) + 1)
        self.count = 0
        self.lag_sum = 0.0
        self.lag_max = 0.0
        self.stalls = 0

    def start(self) -> "LoopLagProbe":
        self.loop.call_soon_threadsafe(self._arm)
        return self

    def _arm(self) -> None:
        if self._stopped or self.loop.is_closed():
            return
        self._expected = self.loop.time() + self.interval_s
        self._handle = self.loop.call_later(self.interval_s, self._tick)

    def _tick(self) -> None:
        lag = max(0.0, self.loop.time() - self._expected)
        self.count += 1
        self.lag_sum += lag
        if lag > self.lag_max:
            self.lag_max = lag
        i = 0
        for i, b in enumerate(self._bounds):
            if lag <= b:
                break
        else:
            i = len(self._bounds)
        self._hist[i] += 1
        from ray_tpu.util import telemetry

        telemetry.observe("ray_tpu_event_loop_lag_seconds", lag,
                          {"proc": self.tag})
        if lag >= self.stall_threshold_s:
            self.stalls += 1
            from ray_tpu.util import flight_recorder

            flight_recorder.record(
                "rpc", "loop_stall", severity=flight_recorder.WARN,
                loop=self.name, lag_s=round(lag, 4))
        self._arm()

    def stop(self) -> None:
        self._stopped = True
        handle, self._handle = self._handle, None
        if handle is not None:
            try:
                self.loop.call_soon_threadsafe(handle.cancel)
            except RuntimeError:  # lint: allow-silent(loop already closed; nothing left to cancel)
                pass

    def summary(self) -> dict:
        from ray_tpu.util.metrics_history import _bucket_percentile

        hist = [float(c) for c in self._hist]
        return {
            "loop": self.name,
            "proc": self.tag,
            "interval_s": self.interval_s,
            "ticks": self.count,
            "lag_avg_s": (round(self.lag_sum / self.count, 6)
                          if self.count else 0.0),
            "lag_max_s": round(self.lag_max, 6),
            "lag_p50_s": _bucket_percentile(self._bounds, hist, 0.50),
            "lag_p99_s": _bucket_percentile(self._bounds, hist, 0.99),
            "stalls": self.stalls,
        }


class AmplificationStats:
    """Head-side pubsub / KV fan-out amplification accounting.

    One instance per head service. A publish to ``n`` subscribers costs
    ``n`` messages and ``n x payload`` bytes; a KV put with downstream
    deliveries costs ``bytes x fan-out``. The per-channel /
    per-namespace tables are code-bounded (channel names and KV
    namespaces are finite in this runtime), so no cap logic is needed —
    the per-caller explosion lives in :class:`ServerStats` where the
    cap is.
    """

    def __init__(self):
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock("rpc_stats.AmplificationStats._lock")
        #: channel -> [publishes, messages, bytes, drops, last_fanout]
        self._channels: Dict[str, list] = {}
        #: ns -> [puts, bytes, amplified_bytes]
        self._kv: Dict[str, list] = {}
        self.pruned_total = 0

    def record_publish(self, channel: str, fanout: int, nbytes: int,
                       pruned: int = 0) -> None:
        with self._lock:
            row = self._channels.setdefault(channel, [0, 0, 0, 0, 0])
            row[0] += 1
            row[1] += fanout
            row[2] += nbytes * fanout
            row[3] += pruned
            row[4] = fanout
            self.pruned_total += pruned
        from ray_tpu.util import telemetry

        if fanout:
            telemetry.inc("ray_tpu_pubsub_messages_total", fanout,
                          {"channel": channel})
            telemetry.inc("ray_tpu_pubsub_bytes_total", nbytes * fanout,
                          {"channel": channel})
        telemetry.set_gauge("ray_tpu_pubsub_fanout", fanout,
                            {"channel": channel})
        if pruned:
            telemetry.inc(
                "ray_tpu_pubsub_dead_subscribers_pruned_total", pruned)

    def record_prune(self, channel: str, pruned: int) -> None:
        """Prunes outside a publish (worker death / conn close)."""
        if pruned <= 0:
            return
        with self._lock:
            row = self._channels.setdefault(channel, [0, 0, 0, 0, 0])
            row[3] += pruned
            self.pruned_total += pruned
        from ray_tpu.util import telemetry

        telemetry.inc("ray_tpu_pubsub_dead_subscribers_pruned_total",
                      pruned)

    def record_kv_put(self, ns: str, nbytes: int, fanout: int) -> None:
        """``fanout`` counts downstream deliveries beyond the store
        write itself (history ingest, watchers); amplification is
        ``bytes x (1 + fanout)``."""
        amplified = nbytes * (1 + max(0, fanout))
        with self._lock:
            row = self._kv.setdefault(ns, [0, 0, 0])
            row[0] += 1
            row[1] += nbytes
            row[2] += amplified
        from ray_tpu.util import telemetry

        telemetry.inc("ray_tpu_kv_write_bytes_total", nbytes,
                      {"ns": ns})
        telemetry.inc("ray_tpu_kv_write_amplified_bytes_total",
                      amplified, {"ns": ns})

    def snapshot(self) -> dict:
        with self._lock:
            channels = [
                {"channel": ch, "publishes": r[0], "messages": r[1],
                 "bytes": r[2], "drops_pruned": r[3],
                 "fanout": r[4],
                 "fanout_avg": round(r[1] / r[0], 3) if r[0] else 0.0}
                for ch, r in self._channels.items()]
            kv = [
                {"ns": ns, "puts": r[0], "bytes": r[1],
                 "amplified_bytes": r[2],
                 "amplification": (round(r[2] / r[1], 3)
                                   if r[1] else 1.0)}
                for ns, r in self._kv.items()]
        channels.sort(key=lambda r: (-r["messages"], r["channel"]))
        kv.sort(key=lambda r: (-r["amplified_bytes"], r["ns"]))
        return {"pubsub": channels, "kv": kv,
                "pruned_total": self.pruned_total}


# -- process-global registries ------------------------------------------

_server_stats: Optional[ServerStats] = None
_stats_lock = threading.Lock()
_probes: Dict[str, LoopLagProbe] = {}
_probes_lock = threading.Lock()


def server_stats() -> ServerStats:
    """The process-global inbound-call accounting table."""
    global _server_stats
    s = _server_stats
    if s is None:
        with _stats_lock:
            s = _server_stats
            if s is None:
                s = _server_stats = ServerStats()
    return s


def install_probe(loop: asyncio.AbstractEventLoop, name: str,
                  interval_s: Optional[float] = None,
                  stall_threshold_s: Optional[float] = None
                  ) -> Optional[LoopLagProbe]:
    """Install (idempotently, by loop name) a lag probe on ``loop``.

    Returns None when the metrics plane is disabled — the probe's only
    output rides telemetry, so a disabled plane should not pay the
    wakeups either."""
    from ray_tpu.util import telemetry

    if not telemetry.enabled():
        return None
    if interval_s is None or stall_threshold_s is None:
        try:
            from ray_tpu.core.config import get_config

            cfg = get_config()
            if interval_s is None:
                interval_s = cfg.event_loop_probe_interval_s
            if stall_threshold_s is None:
                stall_threshold_s = cfg.event_loop_stall_threshold_s
        except Exception:  # lint: allow-silent(config not bootstrapped yet; probe defaults are safe)
            interval_s = interval_s or 0.25
            stall_threshold_s = stall_threshold_s or 0.5
    with _probes_lock:
        probe = _probes.get(name)
        if probe is not None:
            if not probe.loop.is_closed() and probe.loop.is_running():
                return probe
            # Stale probe from a stopped loop (init/shutdown churn):
            # mark it dead and take over the name.
            probe._stopped = True
        probe = LoopLagProbe(loop, name, interval_s=interval_s,
                             stall_threshold_s=stall_threshold_s)
        _probes[name] = probe
    return probe.start()


def probe_summaries() -> List[dict]:
    with _probes_lock:
        probes = list(_probes.values())
    return [p.summary() for p in probes]


def reset_for_testing() -> None:
    global _server_stats
    with _stats_lock:
        _server_stats = None
    with _probes_lock:
        for probe in _probes.values():
            probe.stop()
        _probes.clear()
