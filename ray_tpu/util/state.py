"""State API: cluster introspection.

Reference: python/ray/util/state/api.py (list_actors:782, list_tasks,
list_objects:1060, list_nodes, list_workers, summarize_tasks:1376),
backed by the head's task-event store and live tables.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

from ray_tpu.core.object_ref import get_core_worker


def _call(method: str, payload: Optional[dict] = None):
    cw = get_core_worker()
    if cw is None:
        raise RuntimeError("ray_tpu not initialized")
    return cw.loop_thread.run(cw.head.call(method, payload or {}))


def list_actors(*, filters: Optional[List[tuple]] = None
                ) -> List[Dict[str, Any]]:
    actors = _call("list_actors")["actors"]
    return _apply_filters(actors, filters)


def list_workers() -> List[Dict[str, Any]]:
    return _call("list_workers")


def list_nodes() -> List[Dict[str, Any]]:
    return _call("get_nodes")


def list_objects() -> List[Dict[str, Any]]:
    return _call("list_objects")["objects"]


def list_jobs() -> List[Dict[str, Any]]:
    return _call("list_jobs")["jobs"]


def list_placement_groups() -> List[Dict[str, Any]]:
    return _call("list_pgs")


def list_tasks(*, limit: int = 1000,
               filters: Optional[List[tuple]] = None
               ) -> List[Dict[str, Any]]:
    """Latest state per task, from the task-event store. Filters apply
    BEFORE the limit truncation — filtering a window that was already
    truncated would silently drop matching rows older than the newest
    ``limit`` tasks. Filtered queries fetch the store's whole retained
    window for the same reason (the head ring is bounded by
    ``task_events_max_buffer_size``, so this is capped server-side)."""
    fetch = 10 * limit if not filters else max(10 * limit, 1_000_000)
    events = _call("list_task_events", {"limit": fetch})["events"]
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        latest[ev["task_id"]] = ev
    tasks = _apply_filters(list(latest.values()), filters)
    return tasks[-limit:]


def list_task_events(*, limit: int = 1000) -> List[Dict[str, Any]]:
    return _call("list_task_events", {"limit": limit})["events"]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """Per-function-name counts by state (reference: summarize_tasks)."""
    summary: Dict[str, Dict[str, int]] = collections.defaultdict(
        lambda: collections.defaultdict(int))
    for t in list_tasks(limit=100000):
        summary[t.get("name") or "<anonymous>"][t["state"]] += 1
    return {k: dict(v) for k, v in summary.items()}


def summarize_actors() -> Dict[str, int]:
    out: Dict[str, int] = collections.defaultdict(int)
    for a in list_actors():
        out[a["state"]] += 1
    return dict(out)


def summarize_objects() -> Dict[str, Dict[str, int]]:
    """Cluster store occupancy by object state — ``{state: {"count",
    "bytes"}}`` (states: SEALED / SPILLED / LOST)."""
    summary: Dict[str, Dict[str, int]] = {}
    for obj in list_objects():
        entry = summary.setdefault(obj.get("state", "SEALED"),
                                   {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += int(obj.get("size_bytes") or 0)
    return summary


def _apply_filters(rows: List[dict], filters) -> List[dict]:
    """Filter rows by ``(key, op, value)`` triples. Ops: ``=``/``==``,
    ``!=``, ``in`` (row value ∈ given collection), ``contains`` (given
    value ∈ row's value — substring / membership), ``<`` and ``>``
    (numeric; non-numeric rows never match)."""
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op in ("=", "=="):
                ok = have == value
            elif op == "!=":
                ok = have != value
            elif op == "in":
                try:
                    ok = have in value
                except TypeError:
                    ok = False
            elif op == "contains":
                try:
                    ok = have is not None and value in have
                except TypeError:
                    ok = False
            elif op in ("<", ">"):
                try:
                    have_f, value_f = float(have), float(value)
                except (TypeError, ValueError):
                    ok = False
                else:
                    ok = (have_f < value_f if op == "<"
                          else have_f > value_f)
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out
