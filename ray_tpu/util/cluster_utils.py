"""Fake multi-node cluster for tests.

Reference: python/ray/cluster_utils.py:108 (Cluster.add_node:174,
remove_node:247) — extra logical nodes in one host so multi-node
scheduling semantics (spread, node affinity, gang placement across
hosts) are testable without machines. Workers for every logical node
run as local processes; the scheduler sees distinct nodes with their
own resource pools.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.core.ids import NodeID


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.node_ids: List[NodeID] = []
        if initialize_head:
            ray_tpu.init(**(head_node_args or {}))
        from ray_tpu import api as _api

        self._head = _api._global_node
        if self._head is None:
            raise RuntimeError("cluster requires ray_tpu.init()")

    def add_node(self, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None) -> NodeID:
        res: Dict[str, float] = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        node_id = self._head.add_node(res)
        self.node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: NodeID):
        self._head.remove_node(node_id)
        if node_id in self.node_ids:
            self.node_ids.remove(node_id)

    def list_nodes(self) -> List[dict]:
        from ray_tpu.util.state import list_nodes

        return list_nodes()

    def shutdown(self):
        ray_tpu.shutdown()
