"""Chaos testing: first-class fault injectors.

Reference: _private/test_utils.py:1396 (ResourceKillerActor),
:1527 (WorkerKillerActor) — actors that kill workers/actors on a
schedule, used by chaos test suites to validate fault tolerance.
"""

from __future__ import annotations

import asyncio
import os
import random
import signal
from typing import List, Optional


class WorkerKiller:
    """Async actor that SIGKILLs random task-running worker processes."""

    def __init__(self, kill_interval_s: float = 1.0,
                 max_kills: int = 5, seed: int = 0):
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.rng = random.Random(seed)
        self.killed: List[int] = []
        self._running = False

    async def run(self) -> int:
        import ray_tpu
        from ray_tpu.util.state import list_workers

        self._running = True
        me = os.getpid()
        while self._running and len(self.killed) < self.max_kills:
            await asyncio.sleep(self.kill_interval_s)
            loop = asyncio.get_event_loop()
            workers = await loop.run_in_executor(None, list_workers)
            candidates = [w for w in workers
                          if w["state"] == "LEASED" and w["pid"] != me]
            if not candidates:
                continue
            victim = self.rng.choice(candidates)
            try:
                os.kill(victim["pid"], signal.SIGKILL)
                self.killed.append(victim["pid"])
            except ProcessLookupError:
                pass
        return len(self.killed)

    async def stop(self) -> List[int]:
        self._running = False
        return self.killed

    async def get_killed(self) -> List[int]:
        return list(self.killed)


class ActorKiller:
    """Kills named/visible actors at random (reference: chaos killers
    targeting actors instead of raw workers)."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 3,
                 name_prefix: str = "", seed: int = 0):
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.name_prefix = name_prefix
        self.rng = random.Random(seed)
        self.killed: List[str] = []
        self._running = False

    async def run(self) -> int:
        import ray_tpu
        from ray_tpu.util.state import list_actors

        self._running = True
        while self._running and len(self.killed) < self.max_kills:
            await asyncio.sleep(self.kill_interval_s)
            loop = asyncio.get_event_loop()
            actors = await loop.run_in_executor(None, list_actors)
            candidates = [
                a for a in actors
                if a["state"] == "ALIVE" and a.get("name")
                and a["name"].startswith(self.name_prefix)
                and not a["name"].startswith("_chaos")]
            if not candidates:
                continue
            victim = self.rng.choice(candidates)
            try:
                handle = await loop.run_in_executor(
                    None, lambda: ray_tpu.get_actor(victim["name"]))
                await loop.run_in_executor(
                    None, lambda: ray_tpu.kill(handle))
                self.killed.append(victim["name"])
            except Exception:
                pass
        return len(self.killed)

    async def stop(self) -> List[str]:
        self._running = False
        return self.killed
