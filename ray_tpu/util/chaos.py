"""Chaos testing: first-class fault injectors.

Reference: _private/test_utils.py:1396 (ResourceKillerActor),
:1527 (WorkerKillerActor) — actors that kill workers/actors on a
schedule, used by chaos test suites to validate fault tolerance.

Process-granular killers live here; network-granular faults (drop /
delay / duplicate / partition) live in ``core/rpc.py``'s
``FaultInjector`` — together they form the chaos lane (pytest -m
chaos).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import signal
import time
from typing import Any, List, Optional

logger = logging.getLogger(__name__)


class _KillerBase:
    """Shared schedule/strike loop for the kill actors: seeded RNG,
    kill budget, error counter, and a ``max_duration_s`` deadline so a
    soak run whose candidate set never materializes cannot hang the
    suite. Subclasses implement ``_victims()`` (candidate listing) and
    ``_strike(victim)`` (the kill itself, returning the token recorded
    in ``killed``); ``run()`` is the one poll/choose/strike loop."""

    def __init__(self, kill_interval_s: float, max_kills: int, seed: int,
                 max_duration_s: Optional[float] = None):
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.max_duration_s = max_duration_s
        self.rng = random.Random(seed)
        self.killed: List = []
        # Strike attempts that failed (victim vanished first, lookup
        # errors). Exposed rather than swallowed — a chaos run whose
        # kills all silently failed proves nothing.
        self.errors = 0
        self._running = False
        self._started_at: Optional[float] = None

    def _start_clock(self):
        self._running = True
        self._started_at = time.monotonic()

    def _sleep_s(self) -> float:
        """Next poll sleep, clipped so max_duration_s is honored even
        when the kill interval is longer than the remaining budget."""
        if self.max_duration_s is None:
            return self.kill_interval_s
        remaining = (self.max_duration_s
                     - (time.monotonic() - self._started_at))
        return max(0.0, min(self.kill_interval_s, remaining))

    def _keep_running(self) -> bool:
        if not self._running or len(self.killed) >= self.max_kills:
            return False
        if (self.max_duration_s is not None
                and time.monotonic() - self._started_at
                >= self.max_duration_s):
            logger.debug("%s: max_duration_s=%.1f reached after %d kills",
                         type(self).__name__, self.max_duration_s,
                         len(self.killed))
            return False
        return True

    # -- subclass surface ------------------------------------------------

    def _victims(self) -> List[Any]:
        """Current strike candidates (runs in an executor thread)."""
        raise NotImplementedError

    def _strike(self, victim: Any) -> Any:
        """Kill/disrupt one victim (executor thread); the return value
        is recorded in ``killed``. Raise to count an error instead."""
        raise NotImplementedError

    async def run(self) -> int:
        self._start_clock()
        loop = asyncio.get_event_loop()
        while self._keep_running():
            await asyncio.sleep(self._sleep_s())
            if not self._keep_running():
                break
            try:
                candidates = await loop.run_in_executor(
                    None, self._victims)
            except Exception as e:  # noqa: BLE001 — counted, not hidden
                self.errors += 1
                logger.debug("%s victim listing failed: %s",
                             type(self).__name__, e)
                continue
            if not candidates:
                continue
            victim = self.rng.choice(candidates)
            try:
                token = await loop.run_in_executor(
                    None, lambda: self._strike(victim))
                self.killed.append(token)
                logger.info("%s struck %r", type(self).__name__, token)
            except Exception as e:  # noqa: BLE001 — counted, not hidden
                # Mirror LocalPeer's handler policy: failures are
                # surfaced (counter + debug log), never swallowed — a
                # kill that keeps missing its victim is signal.
                self.errors += 1
                logger.debug("%s strike of %r failed: %s",
                             type(self).__name__, victim, e)
        return len(self.killed)

    async def stop(self) -> List:
        self._running = False
        return self.killed

    async def get_killed(self) -> List:
        return list(self.killed)

    async def get_errors(self) -> int:
        return self.errors


class WorkerKiller(_KillerBase):
    """Async actor that SIGKILLs random task-running worker processes."""

    def __init__(self, kill_interval_s: float = 1.0,
                 max_kills: int = 5, seed: int = 0,
                 max_duration_s: Optional[float] = None):
        super().__init__(kill_interval_s, max_kills, seed, max_duration_s)

    def _victims(self) -> List[dict]:
        from ray_tpu.util.state import list_workers

        me = os.getpid()
        return [w for w in list_workers()
                if w["state"] == "LEASED" and w["pid"] != me]

    def _strike(self, victim: dict) -> int:
        # ProcessLookupError (victim exited between the listing and the
        # kill) propagates to the error counter — not a fault of the
        # killer, but worth counting.
        os.kill(victim["pid"], signal.SIGKILL)
        return victim["pid"]


class ActorKiller(_KillerBase):
    """Kills named/visible actors at random (reference: chaos killers
    targeting actors instead of raw workers)."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 3,
                 name_prefix: str = "", seed: int = 0,
                 max_duration_s: Optional[float] = None):
        super().__init__(kill_interval_s, max_kills, seed, max_duration_s)
        self.name_prefix = name_prefix

    def _victims(self) -> List[dict]:
        from ray_tpu.util.state import list_actors

        return [
            a for a in list_actors()
            if a["state"] == "ALIVE" and a.get("name")
            and a["name"].startswith(self.name_prefix)
            and not a["name"].startswith("_chaos")]

    def _strike(self, victim: dict) -> str:
        import ray_tpu

        handle = ray_tpu.get_actor(victim["name"])
        ray_tpu.kill(handle)
        return victim["name"]


class ReplicaKiller(ActorKiller):
    """Serve-aware chaos lane: kills live ``SERVE_REPLICA::`` actors,
    optionally scoped to one deployment — used by the streaming soak to
    prove a replica death mid-stream surfaces a terminal error chunk to
    clients (never a hang) and that the router reroutes the next
    request. Replica names embed ``<app>#<deployment>#g<gen>#<n>``, so
    ``app``/``deployment`` filters match structurally rather than by
    raw prefix."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 3,
                 app: str = "", deployment: str = "", seed: int = 0,
                 max_duration_s: Optional[float] = None):
        prefix = "SERVE_REPLICA::"
        if app:
            prefix += f"{app}#"
            if deployment:
                prefix += f"{deployment}#"
        super().__init__(kill_interval_s, max_kills, prefix, seed,
                         max_duration_s)


class TrainWorkerKiller(_KillerBase):
    """Train-aware chaos lane: kills or hangs a random ``TrainWorker``
    gang actor mid-run, exercising the trainer's gang health monitor
    (death/hang attribution), crash-consistent checkpoint resume, and
    elastic restart. ``mode="kill"`` destroys the actor outright;
    ``mode="hang"`` stalls the victim's train loop for ``hang_s``
    without touching its RPC lane — heartbeats stay green while
    progress stops, which is exactly the hang signature the monitor
    must catch."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 2,
                 seed: int = 0, mode: str = "kill",
                 hang_s: float = 120.0,
                 max_duration_s: Optional[float] = None):
        if mode not in ("kill", "hang"):
            raise ValueError(f"mode must be 'kill' or 'hang', got {mode!r}")
        super().__init__(kill_interval_s, max_kills, seed, max_duration_s)
        self.mode = mode
        self.hang_s = hang_s

    def _victims(self) -> List[dict]:
        from ray_tpu.util.state import list_actors

        return [a for a in list_actors()
                if a["state"] == "ALIVE"
                and a.get("class_name") == "TrainWorker"]

    def _strike(self, victim: dict) -> str:
        import ray_tpu
        from ray_tpu.api import ActorHandle, _require_worker
        from ray_tpu.core.ids import ActorID

        actor_id = ActorID.from_hex(victim["actor_id"])
        cw = _require_worker()
        if self.mode == "kill":
            cw.kill_actor(actor_id, True)
            return victim["actor_id"]
        # Hang: needs a callable handle — hydrate actor state from the
        # head the same way get_actor does for named actors.
        reply = cw.loop_thread.run(cw.head.call(
            "get_actor_info", {"actor_id": victim["actor_id"]}))
        if not reply.get("found"):
            raise RuntimeError(f"actor {victim['actor_id']} vanished")
        cw._on_actor_state_threadsafe(reply)
        handle = ActorHandle(actor_id)
        ray_tpu.get(handle.chaos_hang.remote(self.hang_s), timeout=10)
        return victim["actor_id"]
