"""Chaos testing: first-class fault injectors.

Reference: _private/test_utils.py:1396 (ResourceKillerActor),
:1527 (WorkerKillerActor) — actors that kill workers/actors on a
schedule, used by chaos test suites to validate fault tolerance.

Process-granular killers live here; network-granular faults (drop /
delay / duplicate / partition) live in ``core/rpc.py``'s
``FaultInjector`` — together they form the chaos lane (pytest -m
chaos).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import signal
import time
from typing import List, Optional

logger = logging.getLogger(__name__)


class _KillerBase:
    """Shared schedule/bookkeeping for the kill actors: seeded RNG,
    kill budget, error counter, and a ``max_duration_s`` deadline so a
    soak run whose candidate set never materializes cannot hang the
    suite."""

    def __init__(self, kill_interval_s: float, max_kills: int, seed: int,
                 max_duration_s: Optional[float] = None):
        self.kill_interval_s = kill_interval_s
        self.max_kills = max_kills
        self.max_duration_s = max_duration_s
        self.rng = random.Random(seed)
        self.killed: List = []
        # Kill attempts that failed (victim vanished first, lookup
        # errors). Exposed rather than swallowed — a chaos run whose
        # kills all silently failed proves nothing.
        self.errors = 0
        self._running = False
        self._started_at: Optional[float] = None

    def _start_clock(self):
        self._running = True
        self._started_at = time.monotonic()

    def _sleep_s(self) -> float:
        """Next poll sleep, clipped so max_duration_s is honored even
        when the kill interval is longer than the remaining budget."""
        if self.max_duration_s is None:
            return self.kill_interval_s
        remaining = (self.max_duration_s
                     - (time.monotonic() - self._started_at))
        return max(0.0, min(self.kill_interval_s, remaining))

    def _keep_running(self) -> bool:
        if not self._running or len(self.killed) >= self.max_kills:
            return False
        if (self.max_duration_s is not None
                and time.monotonic() - self._started_at
                >= self.max_duration_s):
            logger.debug("%s: max_duration_s=%.1f reached after %d kills",
                         type(self).__name__, self.max_duration_s,
                         len(self.killed))
            return False
        return True

    async def stop(self) -> List:
        self._running = False
        return self.killed

    async def get_killed(self) -> List:
        return list(self.killed)

    async def get_errors(self) -> int:
        return self.errors


class WorkerKiller(_KillerBase):
    """Async actor that SIGKILLs random task-running worker processes."""

    def __init__(self, kill_interval_s: float = 1.0,
                 max_kills: int = 5, seed: int = 0,
                 max_duration_s: Optional[float] = None):
        super().__init__(kill_interval_s, max_kills, seed, max_duration_s)

    async def run(self) -> int:
        import ray_tpu
        from ray_tpu.util.state import list_workers

        self._start_clock()
        me = os.getpid()
        while self._keep_running():
            await asyncio.sleep(self._sleep_s())
            if not self._keep_running():
                break
            loop = asyncio.get_event_loop()
            workers = await loop.run_in_executor(None, list_workers)
            candidates = [w for w in workers
                          if w["state"] == "LEASED" and w["pid"] != me]
            if not candidates:
                continue
            victim = self.rng.choice(candidates)
            try:
                os.kill(victim["pid"], signal.SIGKILL)
                self.killed.append(victim["pid"])
            except ProcessLookupError:
                # Victim exited between the listing and the kill — not a
                # fault of the killer, but worth counting.
                self.errors += 1
                logger.debug("worker kill of pid %s failed: gone",
                             victim["pid"])
        return len(self.killed)


class ActorKiller(_KillerBase):
    """Kills named/visible actors at random (reference: chaos killers
    targeting actors instead of raw workers)."""

    def __init__(self, kill_interval_s: float = 1.0, max_kills: int = 3,
                 name_prefix: str = "", seed: int = 0,
                 max_duration_s: Optional[float] = None):
        super().__init__(kill_interval_s, max_kills, seed, max_duration_s)
        self.name_prefix = name_prefix

    async def run(self) -> int:
        import ray_tpu
        from ray_tpu.util.state import list_actors

        self._start_clock()
        while self._keep_running():
            await asyncio.sleep(self._sleep_s())
            if not self._keep_running():
                break
            loop = asyncio.get_event_loop()
            actors = await loop.run_in_executor(None, list_actors)
            candidates = [
                a for a in actors
                if a["state"] == "ALIVE" and a.get("name")
                and a["name"].startswith(self.name_prefix)
                and not a["name"].startswith("_chaos")]
            if not candidates:
                continue
            victim = self.rng.choice(candidates)
            try:
                handle = await loop.run_in_executor(
                    None, lambda: ray_tpu.get_actor(victim["name"]))
                await loop.run_in_executor(
                    None, lambda: ray_tpu.kill(handle))
                self.killed.append(victim["name"])
            except Exception as e:  # noqa: BLE001 — counted, not hidden
                # Mirror LocalPeer's handler policy: failures are
                # surfaced (counter + debug log), never swallowed — a
                # kill that keeps missing its victim is signal.
                self.errors += 1
                logger.debug("actor kill of %r failed: %s",
                             victim["name"], e)
        return len(self.killed)
