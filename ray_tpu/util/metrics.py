"""Application metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py:153,224,299 — app metrics flow to
the node metrics agent and out to Prometheus. Here each process keeps a
local registry and pushes snapshots to the head KV (namespace
"metrics", keyed by worker id); ``collect_metrics`` merges all
processes' snapshots and ``prometheus_text`` renders the standard
exposition format for scraping.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}
_last_push = 0.0
_PUSH_INTERVAL_S = 2.0  # fallback when config is unavailable


def _push_interval() -> float:
    """Config-driven throttle (metrics_report_interval_s)."""
    try:
        from ray_tpu.core.config import get_config

        return float(get_config().metrics_report_interval_s)
    except Exception:  # metrics must work before config bootstraps
        return _PUSH_INTERVAL_S
# Called with the core worker after each metrics push; the telemetry
# module's timeline-event push rides the same throttle window.
_push_hooks: List[Callable] = []


class Metric:
    metric_type = "untyped"

    def __new__(cls, name: str, *args, **kwargs):
        # Idempotent registration: instrumented modules are imported in
        # every process, and two subsystems may declare the same metric;
        # re-creation by name hands back the live instance (keeping its
        # recorded values) instead of silently replacing it in the
        # registry. A name reused across metric TYPES is a programming
        # error and raises. The ENTIRE mutable state is built inside
        # this one lock section — __init__ is a pure declaration merge
        # — so two threads racing the first creation converge on one
        # instance whose value store is never re-created.
        if not name:
            raise ValueError("metric name required")
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                if existing.metric_type != cls.metric_type:
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{existing.metric_type}; cannot re-register as "
                        f"{cls.metric_type}")
                return existing
            inst = super().__new__(cls)
            inst.name = name
            inst.description = ""
            inst.tag_keys = ()
            inst._default_tags = {}
            # frozen tag tuple -> value(s); guarded by _mutex (recorded
            # from executor threads, snapshotted by whichever thread
            # pushes).
            inst._values = {}
            inst._mutex = threading.Lock()
            cls._init_state(inst)
            _registry[name] = inst
            return inst

    @classmethod
    def _init_state(cls, inst):
        """Subclass hook: extra mutable state, created once under the
        registry lock."""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        # Runs on every (re-)creation: merge the declaration, keep the
        # recorded values untouched.
        if description and not self.description:
            self.description = description
        if tag_keys:
            self.tag_keys = tuple(sorted(
                set(self.tag_keys) | set(tag_keys)))

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tag_key(self, tags: Optional[Dict[str, str]]
                 ) -> Tuple[Tuple[str, str], ...]:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"undeclared tag keys {sorted(extra)} for {self.name}")
        return tuple(sorted(merged.items()))

    def _snapshot(self) -> dict:
        with self._mutex:
            values = [[list(k), v] for k, v in self._values.items()]
        return {
            "type": self.metric_type,
            "description": self.description,
            "values": values,
        }


class Counter(Metric):
    metric_type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._tag_key(tags)
        with self._mutex:
            self._values[key] = self._values.get(key, 0.0) + value
        _maybe_push()


class Gauge(Metric):
    metric_type = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._tag_key(tags)
        with self._mutex:
            self._values[key] = float(value)
        _maybe_push()


DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                      2.5, 5.0, 10.0]


class Histogram(Metric):
    metric_type = "histogram"

    @classmethod
    def _init_state(cls, inst):
        inst.boundaries = None  # fixed by the first declaration below
        # tag key -> [bucket counts..., +inf count, sum, count]
        inst._hists = {}

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        with self._mutex:
            if self.boundaries is None:
                self.boundaries = sorted(boundaries or DEFAULT_BOUNDARIES)
            elif boundaries and sorted(boundaries) != self.boundaries:
                raise TypeError(
                    f"histogram {name!r} re-registered with different "
                    f"boundaries")

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        key = self._tag_key(tags)
        with self._mutex:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = (
                    [0] * (len(self.boundaries) + 1) + [0.0, 0])
            idx = bisect.bisect_left(self.boundaries, value)
            h[idx] += 1
            h[-2] += value
            h[-1] += 1
        _maybe_push()

    def _snapshot(self) -> dict:
        with self._mutex:
            hists = [[list(k), list(v)] for k, v in self._hists.items()]
        return {
            "type": self.metric_type,
            "description": self.description,
            "boundaries": self.boundaries,
            "hists": hists,
        }


def register_push_hook(fn: Callable) -> None:
    """Register ``fn(core_worker)`` to run after each metrics push —
    piggyback channel for data that should ride the same throttle (the
    telemetry module pushes its timeline-event buffer this way)."""
    if fn not in _push_hooks:
        _push_hooks.append(fn)


_flush_timer = None
_flush_timer_lock = threading.Lock()

#: Series the metrics push itself moves (the kv_put rides the
#: instrumented RPC path). The trailing-flush quiesce check ignores
#: them — otherwise each push re-dirties the registry and the one-shot
#: trailing flush becomes a perpetual idle heartbeat.
_SELF_NOISE = frozenset({
    "ray_tpu_rpc_sent_bytes_total",
    "ray_tpu_rpc_recv_bytes_total",
    "ray_tpu_rpc_client_latency_seconds",
    "ray_tpu_rpc_in_flight_requests",
})
_last_app_blob: Optional[str] = None


def _schedule_trailing_flush(delay: float) -> None:
    """Arm a one-shot timer so values recorded inside the throttle
    window still reach the KV within one interval — without it, a
    process that records a burst and then goes idle (a Serve proxy
    after its last request) never ships its final counts."""
    global _flush_timer
    if _flush_timer is not None:
        return  # benign race: the locked re-check below is the arbiter
    with _flush_timer_lock:
        if _flush_timer is not None:
            return
        _flush_timer = threading.Timer(delay + 0.05, _trailing_flush)
        _flush_timer.daemon = True
        _flush_timer.start()


def _trailing_flush() -> None:
    global _flush_timer
    with _flush_timer_lock:
        _flush_timer = None
    _maybe_push(force=True, idle_skip=True)


def _maybe_push(force: bool = False, idle_skip: bool = False):
    """Throttled push of this process's registry to the head KV."""
    global _last_push, _last_app_blob
    now = time.time()
    interval = _push_interval()
    if not force and now - _last_push < interval:
        _schedule_trailing_flush(interval - (now - _last_push))
        return
    try:
        from ray_tpu.core.object_ref import get_core_worker

        cw = get_core_worker()
        if cw is None:
            # Leave _last_push untouched: a process that records metrics
            # before its worker is up must not consume the throttle
            # window, or the first real push is delayed by a full
            # interval.
            return
        with _registry_lock:
            snap = {name: m._snapshot() for name, m in _registry.items()}
        app_blob = json.dumps(
            {k: v for k, v in snap.items() if k not in _SELF_NOISE},
            sort_keys=True)
        if idle_skip and app_blob == _last_app_blob:
            # Trailing flush with nothing new beyond our own push
            # traffic: skip the registry write, but still run the push
            # hooks — a hook may have piggyback data armed inside the
            # throttle window (the flight-recorder ring ship) whose
            # delivery guarantee is exactly this flush. Then quiesce
            # (the next real record re-arms).
            for hook in list(_push_hooks):
                try:
                    hook(cw)
                except Exception:  # lint: allow-silent(hooks are best-effort; a failing hook must not break the flush)
                    pass
            return
        _last_push = now
        _last_app_blob = app_blob
        # "_meta" rides OUTSIDE the app_blob comparison above: it
        # changes every push, so including it would turn the one-shot
        # trailing flush into a perpetual idle heartbeat.
        blob = json.dumps(dict(snap, _meta=push_meta(now))).encode()
        key = f"metrics:{cw.worker_id.hex()}".encode()
        cw.loop_thread.submit(cw.head.call("kv_put", {
            "ns": "metrics", "key": key, "value": blob,
            "overwrite": True,
        }))
        for hook in list(_push_hooks):
            try:
                hook(cw)
            except Exception:
                pass
    except Exception:
        pass


def flush_metrics():
    _maybe_push(force=True)


def local_snapshot() -> Dict[str, dict]:
    """This process's registry as push-shaped snapshots — for hosts
    that own the KV directly (a standalone head has no CoreWorker to
    push through)."""
    with _registry_lock:
        return {name: m._snapshot() for name, m in _registry.items()}


def push_meta(now: Optional[float] = None) -> dict:
    """The ``_meta`` stanza attached to every pushed snapshot: who
    wrote it and when, so merge surfaces can age it instead of
    presenting a dead process's last write as current."""
    return {"ts": time.time() if now is None else now, "pid": os.getpid()}


def staleness_window_s() -> float:
    """Config-driven snapshot-staleness horizon (metrics_staleness_s)."""
    try:
        from ray_tpu.core.config import get_config

        return float(get_config().metrics_staleness_s)
    except Exception:  # metrics must work before config bootstraps
        return 15.0


def _fetch_snapshots() -> Dict[str, dict]:
    """Raw per-process push snapshots from the head KV, keyed by the
    KV key ("metrics:<worker id hex>" / "metrics:head")."""
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    if cw is None:
        raise RuntimeError("ray_tpu not initialized")
    keys = cw.loop_thread.run(
        cw.head.call("kv_keys", {"ns": "metrics", "prefix": b"metrics:"}))
    snaps: Dict[str, dict] = {}
    for key in keys.get("keys", []):
        reply = cw.loop_thread.run(
            cw.head.call("kv_get", {"ns": "metrics", "key": key}))
        blob = reply.get("value")
        if not blob:
            continue
        snaps[bytes(key).decode()] = json.loads(bytes(blob).decode())
    return snaps


def merge_snapshots(snaps: Dict[str, dict],
                    now: Optional[float] = None,
                    staleness_s: Optional[float] = None):
    """Merge push-shaped snapshots into the ``collect_metrics`` shape,
    staleness-aware. Counters and histogram buckets sum; a gauge series
    is taken from the FRESHEST writer (by the pushed ``_meta`` ts)
    rather than KV iteration order, so a dead worker's last write can
    never shadow a live one.

    Returns ``(merged, procs, stale)``: ``procs`` is one row per
    snapshot (proc key, push ts, age, stale flag), ``stale`` maps
    metric name -> [tag tuple, ...] for gauge series whose freshest
    writer is itself past the staleness window — surfaces flag those
    instead of presenting them as current.
    """
    now = time.time() if now is None else now
    window = staleness_window_s() if staleness_s is None else staleness_s
    merged: Dict[str, dict] = {}
    gauge_ts: Dict[tuple, float] = {}
    procs: List[dict] = []
    for proc_key, snap in sorted(snaps.items()):
        meta = snap.get("_meta") or {}
        ts = float(meta.get("ts") or 0.0)
        age = (now - ts) if ts else None
        procs.append({
            "proc": proc_key,
            "ts": ts or None,
            "age_s": round(age, 3) if age is not None else None,
            "stale": bool(age is not None and age > window),
        })
        for name, data in snap.items():
            if name == "_meta" or not isinstance(data, dict):
                continue
            dst = merged.setdefault(name, {
                "type": data["type"],
                "description": data.get("description", ""),
                "values": {},
            })
            if data["type"] == "histogram":
                dst.setdefault("boundaries", data.get("boundaries"))
                for k, h in data.get("hists", []):
                    tk = tuple(tuple(p) for p in k)
                    cur = dst["values"].get(tk)
                    dst["values"][tk] = ([a + b for a, b in zip(cur, h)]
                                         if cur else list(h))
            else:
                for k, v in data.get("values", []):
                    tk = tuple(tuple(p) for p in k)
                    if data["type"] == "counter":
                        dst["values"][tk] = dst["values"].get(tk, 0.0) + v
                    else:  # gauge: freshest writer wins
                        prev_ts = gauge_ts.get((name, tk))
                        if prev_ts is None or ts >= prev_ts:
                            dst["values"][tk] = v
                            gauge_ts[(name, tk)] = ts
    stale: Dict[str, list] = {}
    for (name, tk), ts in gauge_ts.items():
        if ts and now - ts > window:
            stale.setdefault(name, []).append(tk)
    return merged, procs, stale


def collect_metrics() -> Dict[str, dict]:
    """Merge all processes' metric snapshots (driver-side)."""
    merged, _procs, _stale = merge_snapshots(_fetch_snapshots())
    return merged


def collect_metrics_detailed() -> dict:
    """``collect_metrics`` plus provenance: per-proc snapshot ages and
    the gauge series whose freshest writer is past the staleness
    window."""
    merged, procs, stale = merge_snapshots(_fetch_snapshots())
    return {"merged": merged, "procs": procs, "stale": stale}


def prometheus_text() -> str:
    """Render the cluster's merged metrics in Prometheus exposition
    format (reference: the metrics agent's OpenCensus->Prometheus
    proxy)."""
    merged, procs, stale = merge_snapshots(_fetch_snapshots())
    return render_prometheus(merged, procs=procs, stale=stale)


def render_prometheus(merged: Dict[str, dict],
                      procs: Optional[List[dict]] = None,
                      stale: Optional[Dict[str, list]] = None) -> str:
    """Render a ``collect_metrics``-shaped dict as Prometheus text.
    With provenance, snapshot ages lead as comments and stale gauge
    series get a ``# STALE`` comment above their sample line."""
    out: List[str] = []
    if procs:
        for p in procs:
            age = (f"{p['age_s']:.1f}s" if p.get("age_s") is not None
                   else "unknown")
            flag = " STALE" if p.get("stale") else ""
            out.append(f"# ray_tpu snapshot {p['proc']} age={age}{flag}")

    def fmt_tags(tk) -> str:
        if not tk:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in tk)
        return "{" + inner + "}"

    for name, data in sorted(merged.items()):
        out.append(f"# HELP {name} {data['description']}")
        out.append(f"# TYPE {name} {data['type']}")
        if data["type"] == "histogram":
            bounds = data.get("boundaries") or []
            for tk, h in data["values"].items():
                acc = 0
                for b, c in zip(bounds, h):
                    acc += c
                    tags = dict(tk)
                    tags["le"] = str(b)
                    out.append(f"{name}_bucket"
                               f"{fmt_tags(tuple(sorted(tags.items())))}"
                               f" {acc}")
                acc += h[len(bounds)]
                tags = dict(tk)
                tags["le"] = "+Inf"
                out.append(f"{name}_bucket"
                           f"{fmt_tags(tuple(sorted(tags.items())))} {acc}")
                out.append(f"{name}_sum{fmt_tags(tk)} {h[-2]}")
                out.append(f"{name}_count{fmt_tags(tk)} {h[-1]}")
        else:
            stale_series = set(map(tuple, (stale or {}).get(name, ())))
            for tk, v in data["values"].items():
                if tk in stale_series:
                    out.append(f"# STALE series below: freshest writer "
                               f"last pushed > "
                               f"{staleness_window_s():.0f}s ago")
                out.append(f"{name}{fmt_tags(tk)} {v}")
    return "\n".join(out) + "\n"
