"""Application metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py:153,224,299 — app metrics flow to
the node metrics agent and out to Prometheus. Here each process keeps a
local registry and pushes snapshots to the head KV (namespace
"metrics", keyed by worker id); ``collect_metrics`` merges all
processes' snapshots and ``prometheus_text`` renders the standard
exposition format for scraping.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: Dict[str, "Metric"] = {}
_last_push = 0.0
_PUSH_INTERVAL_S = 2.0


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name:
            raise ValueError("metric name required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        # frozen tag tuple -> value(s); guarded by _mutex (recorded from
        # executor threads, snapshotted by whichever thread pushes).
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._mutex = threading.Lock()
        with _registry_lock:
            _registry[name] = self

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _tag_key(self, tags: Optional[Dict[str, str]]
                 ) -> Tuple[Tuple[str, str], ...]:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(
                f"undeclared tag keys {sorted(extra)} for {self.name}")
        return tuple(sorted(merged.items()))

    def _snapshot(self) -> dict:
        with self._mutex:
            values = [[list(k), v] for k, v in self._values.items()]
        return {
            "type": self.metric_type,
            "description": self.description,
            "values": values,
        }


class Counter(Metric):
    metric_type = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._tag_key(tags)
        with self._mutex:
            self._values[key] = self._values.get(key, 0.0) + value
        _maybe_push()


class Gauge(Metric):
    metric_type = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._tag_key(tags)
        with self._mutex:
            self._values[key] = float(value)
        _maybe_push()


DEFAULT_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                      2.5, 5.0, 10.0]


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or DEFAULT_BOUNDARIES)
        # tag key -> [bucket counts..., +inf count, sum, count]
        self._hists: Dict[tuple, list] = {}

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        key = self._tag_key(tags)
        with self._mutex:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = (
                    [0] * (len(self.boundaries) + 1) + [0.0, 0])
            idx = bisect.bisect_left(self.boundaries, value)
            h[idx] += 1
            h[-2] += value
            h[-1] += 1
        _maybe_push()

    def _snapshot(self) -> dict:
        with self._mutex:
            hists = [[list(k), list(v)] for k, v in self._hists.items()]
        return {
            "type": self.metric_type,
            "description": self.description,
            "boundaries": self.boundaries,
            "hists": hists,
        }


def _maybe_push(force: bool = False):
    """Throttled push of this process's registry to the head KV."""
    global _last_push
    now = time.time()
    if not force and now - _last_push < _PUSH_INTERVAL_S:
        return
    _last_push = now
    try:
        from ray_tpu.core.object_ref import get_core_worker

        cw = get_core_worker()
        if cw is None:
            return
        with _registry_lock:
            snap = {name: m._snapshot() for name, m in _registry.items()}
        blob = json.dumps(snap).encode()
        key = f"metrics:{cw.worker_id.hex()}".encode()
        cw.loop_thread.submit(cw.head.call("kv_put", {
            "ns": "metrics", "key": key, "value": blob,
            "overwrite": True,
        }))
    except Exception:
        pass


def flush_metrics():
    _maybe_push(force=True)


def collect_metrics() -> Dict[str, dict]:
    """Merge all processes' metric snapshots (driver-side)."""
    import ray_tpu
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    if cw is None:
        raise RuntimeError("ray_tpu not initialized")
    keys = cw.loop_thread.run(
        cw.head.call("kv_keys", {"ns": "metrics", "prefix": b"metrics:"}))
    merged: Dict[str, dict] = {}
    for key in keys.get("keys", []):
        reply = cw.loop_thread.run(
            cw.head.call("kv_get", {"ns": "metrics", "key": key}))
        blob = reply.get("value")
        if not blob:
            continue
        snap = json.loads(bytes(blob).decode())
        for name, data in snap.items():
            dst = merged.setdefault(name, {
                "type": data["type"],
                "description": data.get("description", ""),
                "values": {},
            })
            if data["type"] == "histogram":
                dst.setdefault("boundaries", data.get("boundaries"))
                for k, h in data.get("hists", []):
                    tk = tuple(tuple(p) for p in k)
                    cur = dst["values"].get(tk)
                    dst["values"][tk] = ([a + b for a, b in zip(cur, h)]
                                         if cur else list(h))
            else:
                for k, v in data.get("values", []):
                    tk = tuple(tuple(p) for p in k)
                    if data["type"] == "counter":
                        dst["values"][tk] = dst["values"].get(tk, 0.0) + v
                    else:  # gauge: last write wins
                        dst["values"][tk] = v
    return merged


def prometheus_text() -> str:
    """Render merged metrics in Prometheus exposition format (reference:
    the metrics agent's OpenCensus->Prometheus proxy)."""
    out: List[str] = []

    def fmt_tags(tk) -> str:
        if not tk:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in tk)
        return "{" + inner + "}"

    for name, data in sorted(collect_metrics().items()):
        out.append(f"# HELP {name} {data['description']}")
        out.append(f"# TYPE {name} {data['type']}")
        if data["type"] == "histogram":
            bounds = data.get("boundaries") or []
            for tk, h in data["values"].items():
                acc = 0
                for b, c in zip(bounds, h):
                    acc += c
                    tags = dict(tk)
                    tags["le"] = str(b)
                    out.append(f"{name}_bucket"
                               f"{fmt_tags(tuple(sorted(tags.items())))}"
                               f" {acc}")
                acc += h[len(bounds)]
                tags = dict(tk)
                tags["le"] = "+Inf"
                out.append(f"{name}_bucket"
                           f"{fmt_tags(tuple(sorted(tags.items())))} {acc}")
                out.append(f"{name}_sum{fmt_tags(tk)} {h[-2]}")
                out.append(f"{name}_count{fmt_tags(tk)} {h[-1]}")
        else:
            for tk, v in data["values"].items():
                out.append(f"{name}{fmt_tags(tk)} {v}")
    return "\n".join(out) + "\n"
