"""ActorPool (reference: python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._inflight = {}
        self._result_futures = {}
        self._submit_seq = 0
        self._yield_seq = 0
        self._backlog: List[tuple] = []

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._inflight[future] = (self._submit_seq, actor)
            self._result_futures[self._submit_seq] = future
            self._submit_seq += 1
        else:
            self._backlog.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._result_futures) or bool(self._backlog)

    def get_next(self, timeout=None):
        """Next result in submission order."""
        if self._yield_seq >= self._submit_seq \
                and not self._backlog:
            raise StopIteration("no pending results")
        while self._yield_seq not in self._result_futures:
            if not self._backlog:
                raise StopIteration("no pending results")
            self._drain_one()
        future = self._result_futures[self._yield_seq]
        # Wait BEFORE mutating any pool state: a timeout must leave the
        # result fetchable and the actor accounted for.
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        del self._result_futures[self._yield_seq]
        self._yield_seq += 1
        value = ray_tpu.get(future)
        self._return_actor(future)
        return value

    def get_next_unordered(self, timeout=None):
        """Any completed result."""
        if not self.has_next():
            raise StopIteration("no pending results")
        if not self._inflight and self._backlog:
            self._drain_one()
        ready, _ = ray_tpu.wait(list(self._inflight),
                                num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        index, _ = self._inflight[future]
        del self._result_futures[index]
        value = ray_tpu.get(future)
        self._return_actor(future)
        return value

    def _drain_one(self):
        # No idle actors by definition here; wait for any completion and
        # free that actor for the pending-submit queue (the completed
        # result stays fetchable in _result_futures).
        ready, _ = ray_tpu.wait(list(self._inflight),
                                num_returns=1, timeout=None)
        self._return_actor(ready[0])

    def _return_actor(self, future):
        entry = self._inflight.pop(future, None)
        if entry is None:
            return
        _, actor = entry
        self._idle.append(actor)
        if self._backlog:
            fn, value = self._backlog.pop(0)
            self.submit(fn, value)

    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor: Any):
        self._idle.append(actor)
