"""Always-on, near-zero-cost flight recorder.

Reference: Ray's task-event / state-API plane plus ``ray stack`` — when
a soak stalls or a chaos run dies, metrics say *that* something is
wrong; reconstructing *why* needs the sequence of decisions every layer
took. This module keeps a fixed-size per-process ring of structured
events ``(ts, subsystem, event, severity, tags)`` appended from the hot
paths of every layer: scheduler placement decisions and wait reasons,
object lifecycle (spill/restore/pull/free/recover), RPC
retry/breaker/fault-injection outcomes, GCS node-state transitions,
collective group create/destroy, train gang health and Serve shedding.

Hot-path contract (the acceptance bar): ``record()`` is one cached
enabled-bool check plus a single append to a preallocated
``collections.deque(maxlen=...)`` — deque appends are atomic in
CPython, so NO lock is taken on the record path and none is ever held
across I/O. ``snapshot()`` (the cold read path) copies the ring,
retrying the rare concurrent-mutation race.

The (subsystem, event) namespace is pinned by ``CATALOG`` and linted by
tests/test_flight_recorder.py: call sites must use literal names from
the catalog, so names can't drift or collide as instrumentation grows.
Variable data (ids, counts, reasons) goes in the ``tags``.

On top of the ring, the debug plane (CoreWorker/node-agent
``debug_dump`` RPC, ``ray_tpu debug`` CLI) ships ring contents plus
``dump_stacks()`` (live frames of every thread) cluster-wide, and
``install_crash_handler()`` flushes the ring to a postmortem file in
the worker log dir when a process dies to an unhandled exception.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

INFO = "info"
WARN = "warn"
ERROR = "error"
SEVERITIES = (INFO, WARN, ERROR)

#: subsystem -> allowed event names. The single source of truth for the
#: recorder namespace; the tier-1 lint in tests/test_flight_recorder.py
#: statically checks every ``record(...)`` call site against this table
#: (and that every declared event is actually recorded somewhere).
CATALOG: Dict[str, tuple] = {
    # core/scheduler.py + core/gcs.py lease plane
    "sched": ("lease_granted", "lease_wait", "lease_infeasible"),
    # object lifecycle (core/object_store.py, core/object_transfer.py,
    # core/core_worker.py)
    "object": ("sealed", "spilled", "restored", "pulled", "freed",
               "lost", "recovered", "shard_pulled", "shard_donated"),
    # core/rpc.py + core/retry.py; "loop_stall" is the event-loop lag
    # probe (util/rpc_stats.py) catching a scheduled-vs-actual delay
    # past the stall threshold — the per-process evidence trail behind
    # the ray_tpu_event_loop_lag_seconds histogram.
    "rpc": ("fault_injected", "conn_lost", "retry",
            "deadline_exhausted", "breaker_open", "breaker_closed",
            "loop_stall"),
    # core/gcs.py cluster membership + pubsub hygiene
    "gcs": ("node_alive", "node_suspect", "node_dead",
            "node_reattached", "worker_dead", "actor_state",
            "subscriber_pruned"),
    # collective/collective.py
    "collective": ("group_created", "group_destroyed"),
    # train/backend_executor.py + train/trainer.py;
    # "step_heartbeat_stale" is the gang monitor attributing a stale
    # device step-counter heartbeat (step + phase in the tags) right
    # before the hang abort fires.
    "train": ("heartbeat_miss", "gang_abort", "gang_restart",
              "elastic_resize", "step_heartbeat_stale"),
    # serve/router.py (streaming lifecycle rides the router — it sees
    # both the HTTP proxy's streams and driver-side handle streams);
    # "autoscale" is recorded by the controller on every replica-target
    # change (direction/reason/from/to in the tags).
    "serve": ("replica_shed", "stream_started", "stream_aborted",
              "autoscale"),
    # serve/engine/core.py continuous-batching lifecycle: a sequence is
    # admitted into the running batch between decode iterations and
    # evicted when it finishes, errors, or its client disconnects.
    "engine": ("admitted", "evicted"),
    # the debug plane itself (util/flight_recorder.py)
    "debug": ("postmortem",),
    # live profiling plane (util/profiler.py): an on-demand capture
    # window completed in this process.
    "profile": ("captured",),
    # device trace plane (util/device_trace.py): a jax.profiler
    # capture window completed / failed (concurrent-capture rejection,
    # missing backend, oversized or corrupt trace) in this process.
    "trace": ("captured", "capture_failed"),
    # ring shipping (this module): this process's ring tail was pushed
    # to the head KV after a severity>=error event, so a later SIGKILL
    # still leaves evidence in debug_dump_cluster.
    "fr": ("ring_shipped",),
    # swallowed-exception audit (tools/analysis silent-except checker):
    # sites converted from `except Exception: pass` record the error
    # they drop here, so "nothing happened" still leaves evidence.
    "guard": ("swallowed",),
    # util/locks.py lockdep witness: a lock-order inversion was
    # detected at acquire time (before the deadlock interleaving).
    "lockdep": ("inversion",),
    # util/alerts.py SLO rule engine (head-side): an alert rule crossed
    # into firing or back to resolved; the offending series window
    # rides in the tags as evidence.
    "alert": ("fired", "resolved"),
}

_DEFAULT_CAPACITY = 2048

_enabled: Optional[bool] = None
_ring: Optional[collections.deque] = None
# Guards ring (re)creation and snapshot retries only — NEVER taken by
# record()'s append.
_setup_lock = threading.Lock()


def enabled() -> bool:
    """Cached per-process switch (config ``flight_recorder_enabled`` /
    ``RAY_TPU_FLIGHT_RECORDER_ENABLED``). Default on — the recorder is
    the post-mortem evidence plane; its idle cost is one deque append."""
    global _enabled
    if _enabled is None:
        try:
            from ray_tpu.core.config import get_config

            _enabled = bool(get_config().flight_recorder_enabled)
        except Exception:
            _enabled = os.environ.get(
                "RAY_TPU_FLIGHT_RECORDER_ENABLED", "1").lower() not in (
                    "0", "false", "no")
    return _enabled


def _capacity() -> int:
    try:
        from ray_tpu.core.config import get_config

        return max(16, int(get_config().flight_recorder_capacity))
    except Exception:
        try:
            return max(16, int(os.environ.get(
                "RAY_TPU_FLIGHT_RECORDER_CAPACITY", _DEFAULT_CAPACITY)))
        except ValueError:
            return _DEFAULT_CAPACITY


def _get_ring() -> collections.deque:
    global _ring
    ring = _ring
    if ring is None:
        with _setup_lock:
            if _ring is None:
                _ring = collections.deque(maxlen=_capacity())
            ring = _ring
    return ring


def record(subsystem: str, event: str, severity: str = INFO,
           **tags: Any) -> None:
    """Append one event. ``subsystem`` and ``event`` MUST be literal
    names from ``CATALOG`` (lint-enforced); variable detail rides in
    ``tags``. Hot-path cost when enabled: one time() call + one atomic
    deque append; when disabled: one cached bool check. Error-severity
    events additionally request a ring ship to the head (rare by
    construction, and throttled by the metrics push window)."""
    if not enabled():
        return
    ring = _ring
    if ring is None:
        ring = _get_ring()
    ring.append((time.time(), subsystem, event, severity, tags or None))
    if severity == ERROR:
        _request_ship()


def swallow(site: str, error: BaseException,
            severity: str = WARN, **tags: Any) -> None:
    """Record an intentionally-swallowed exception — the silent-except
    audit's sanctioned alternative to ``except Exception: pass``. The
    handler stays non-fatal, but the drop leaves evidence the debug
    plane can replay (``guard/swallowed`` with the site and error)."""
    record("guard", "swallowed", severity=severity, site=site,
           error=f"{type(error).__name__}: {error}"[:240], **tags)


def snapshot(limit: Optional[int] = None) -> List[dict]:
    """The ring as a list of dicts, oldest first. Copying may race a
    concurrent append (CPython raises on mutation-during-iteration);
    retry a few times, then fall back to a locked copy-by-pop-free
    best effort."""
    ring = _ring
    if ring is None:
        return []
    # record() is deliberately lock-free, so nothing can quiesce the
    # writers; just retry the copy. Each attempt only fails if an
    # append lands mid-iteration, so consecutive failures decay
    # geometrically — 20 in a row is effectively impossible.
    items = None
    for _ in range(20):
        try:
            items = list(ring)
            break
        except RuntimeError:
            continue
    if items is None:
        return []
    if limit is not None:
        items = items[-limit:]
    out = []
    for ts, subsystem, event, severity, tags in items:
        row = {"ts": ts, "subsystem": subsystem, "event": event,
               "severity": severity}
        if tags:
            row["tags"] = {k: _coerce(v) for k, v in tags.items()}
        out.append(row)
    return out


def _coerce(value: Any):
    """Tags must survive msgpack/json on the debug plane."""
    if value is None or isinstance(value, (str, int, float, bool)):
        return value
    return str(value)


def reset_for_testing(capacity: Optional[int] = None) -> None:
    """Drop cached state; optionally pin a new ring capacity."""
    global _enabled, _ring, _ship_pending
    with _setup_lock:
        _enabled = None
        _ship_pending = False
        if capacity is not None:
            _ring = collections.deque(maxlen=max(1, capacity))
        else:
            _ring = None


# ---------------------------------------------------------------------------
# ring shipping (evidence that survives SIGKILL)
# ---------------------------------------------------------------------------
#
# The ring lives in process memory, so a SIGKILL'd worker used to take
# its evidence with it. On any severity>=error event the ring TAIL is
# shipped to the head KV (namespace "flightring", which the head keeps
# past worker death) riding the metrics push throttle — a bounded batch
# per window, no extra RPC cadence. ``debug_dump_cluster`` merges these
# shipped rings for processes it can no longer reach.

_SHIP_TAIL = 256
_ship_pending = False
_ship_hook_installed = False


def _request_ship() -> None:
    """Mark the ring dirty and nudge the metrics pusher; the actual
    ship happens inside the (throttled) push, whose trailing flush
    guarantees delivery within one interval."""
    global _ship_pending
    _ship_pending = True
    try:
        _install_ship_hook()
        from ray_tpu.util import metrics as _metrics

        _metrics._maybe_push()
    except Exception:  # lint: allow-silent(recorder hot path must never raise)
        pass


def _install_ship_hook() -> None:
    global _ship_hook_installed
    if _ship_hook_installed:
        return
    _ship_hook_installed = True
    from ray_tpu.util import metrics as _metrics

    _metrics.register_push_hook(_ship_ring)


def _ship_call(cw) -> tuple:
    """(coroutine, event count) for one ring-tail ship — the single
    place that knows the payload shape, key format, and namespace."""
    payload = {
        "pid": os.getpid(),
        "node_id": os.environ.get("RAY_TPU_NODE_ID"),
        "ts": time.time(),
        "events": snapshot(limit=_SHIP_TAIL),
    }
    coro = cw.head.call("kv_put", {
        "ns": "flightring",
        "key": f"fr:{cw.worker_id.hex()}".encode(),
        "value": json.dumps(payload).encode(),
        "overwrite": True,
    })
    return coro, len(payload["events"])


def _ship_ring(cw) -> None:
    """Metrics push hook: ship this process's ring tail to the head KV
    when an error event armed the flag (fire-and-forget on the loop
    thread — the push path must not block on the head)."""
    global _ship_pending
    if not _ship_pending:
        return
    _ship_pending = False
    try:
        coro, n_events = _ship_call(cw)
        cw.loop_thread.submit(coro)
        record("fr", "ring_shipped", events=n_events)
    except Exception as e:
        swallow("flight_recorder.ship_ring", e)


def ship_ring_now(timeout_s: float = 5.0) -> bool:
    """Synchronously ship the ring tail (blocks until the head acks).
    The deterministic variant for chaos hooks and tests — the throttled
    path can't promise the write lands before a SIGKILL does."""
    from ray_tpu.core.object_ref import get_core_worker

    cw = get_core_worker()
    if cw is None:
        return False
    try:
        coro, n_events = _ship_call(cw)
        cw.loop_thread.run(coro, timeout=timeout_s)
    except Exception as e:
        swallow("flight_recorder.ship_ring_now", e)
        return False
    record("fr", "ring_shipped", events=n_events)
    return True


# ---------------------------------------------------------------------------
# live stacks (the `ray stack` analog, stdlib-only)
# ---------------------------------------------------------------------------

def dump_stacks() -> Dict[str, List[str]]:
    """Current stacks of every thread in this process, formatted —
    ``{"<thread name> (<ident>)": [frame lines...]}``. Like a
    faulthandler dump but returned as data instead of written to an fd,
    so it can ride the debug-dump RPC."""
    threads = {t.ident: t for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        t = threads.get(ident)
        name = f"{t.name if t is not None else '?'} ({ident})"
        try:
            lines = traceback.format_stack(frame)
        except Exception:
            lines = ["<unreadable stack>\n"]
        out[name] = [ln.rstrip("\n") for ln in lines]
    return out


# ---------------------------------------------------------------------------
# crash postmortem
# ---------------------------------------------------------------------------

def postmortem_dir() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR")
    if base:
        return os.path.join(base, "logs")
    import tempfile

    return os.path.join(tempfile.gettempdir(), "ray_tpu", "postmortem")


def flush_postmortem(reason: str, out_dir: Optional[str] = None
                     ) -> Optional[str]:
    """Write the ring + all-thread stacks to
    ``<log dir>/postmortem-<pid>.json``; returns the path (None when
    the write itself fails — a crashing process must never crash harder
    in its crash handler)."""
    record("debug", "postmortem", severity=ERROR, reason=reason[:500])
    path = os.path.join(out_dir or postmortem_dir(),
                        f"postmortem-{os.getpid()}.json")
    payload = {
        "pid": os.getpid(),
        "ts": time.time(),
        "reason": reason,
        "worker_id": os.environ.get("RAY_TPU_WORKER_ID"),
        "node_id": os.environ.get("RAY_TPU_NODE_ID"),
        "events": snapshot(),
        "stacks": dump_stacks(),
    }
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


_crash_handler_installed = False


def install_crash_handler() -> None:
    """Chain onto ``sys.excepthook`` / ``threading.excepthook`` so an
    unhandled crash anywhere in the process flushes the ring as a
    postmortem file before the interpreter dies. Idempotent."""
    global _crash_handler_installed
    if _crash_handler_installed:
        return
    _crash_handler_installed = True
    prev_sys = sys.excepthook

    def on_crash(exc_type, exc, tb):
        try:
            flush_postmortem(f"{exc_type.__name__}: {exc}")
        except Exception:  # lint: allow-silent(crash handler must never crash harder)
            pass
        prev_sys(exc_type, exc, tb)

    sys.excepthook = on_crash
    prev_thread = threading.excepthook

    def on_thread_crash(args):
        # SystemExit from daemon threads is routine teardown, not a
        # crash worth a postmortem.
        if args.exc_type is not SystemExit:
            try:
                flush_postmortem(
                    f"{args.exc_type.__name__}: {args.exc_value} "
                    f"(thread {getattr(args.thread, 'name', '?')})")
            except Exception:  # lint: allow-silent(crash handler must never crash harder)
                pass
        prev_thread(args)

    threading.excepthook = on_thread_crash
