"""Bounded per-series time-series history for the cluster health plane.

Reference: Ray's GCS-centred control plane exists to make cluster state
observable over time (arXiv:1712.05889), and the dashboard keeps short
metric histories head-side for exactly this reason; TPU serving
evaluations are framed as SLOs sustained over windows (TTFT
percentiles under load), which needs trend data, not last-write-wins
gauges. This module is the storage half: every metrics push that lands
in the head KV is diffed against the previous snapshot and appended
into fixed-size rings keyed by (metric name, tag set).

Design constraints, in order:

- **Hard memory bound.** Rings are fixed-size deques; beyond that, an
  approximate byte budget evicts least-recently-updated series whole
  (``evictions`` counts them) — on a 50-node soak the history store
  must never become the thing that kills the head.
- **O(changed series) append cost per push.** Counters and histograms
  are diffed per-proc against the last snapshot and only appended when
  the delta is non-zero; gauges only when the value changed. A fully
  idle cluster appends nothing.
- **Step-down downsampling.** Each series keeps a fine ring (every
  change) plus a coarse ring (one point per ``coarse_interval_s``), so
  a multi-hour window still renders without a multi-hour fine ring.

Counter/histogram snapshots are cumulative PER PROCESS; the store
keeps per-proc last values and appends the cluster-merged running
value, so window ``delta``/``rate`` answers are cluster-wide. A
process's FIRST snapshot seeds its baseline without appending (its
pre-history counts are not a burst that just happened); when a series
first appears after seeding, a zero point is recorded just before the
first real one so window deltas over the series' birth are exact.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

#: Approximate cost model for the byte budget (a [ts, value] pair plus
#: list overhead; histogram points carry the merged bucket vector).
_POINT_COST = 64
_HIST_SLOT_COST = 16
_SERIES_BASE_COST = 512

TagTuple = Tuple[Tuple[str, str], ...]


def _tag_tuple(pairs) -> TagTuple:
    return tuple(sorted((str(k), str(v)) for k, v in pairs))


def _tags_match(tags: TagTuple, want: Optional[Dict[str, str]]) -> bool:
    if not want:
        return True
    d = dict(tags)
    return all(d.get(k) == str(v) for k, v in want.items())


class _Series:
    __slots__ = ("name", "kind", "tags", "recent", "coarse",
                 "last_coarse_ts", "last_value", "merged", "procs",
                 "boundaries", "point_cost")

    def __init__(self, name: str, kind: str, tags: TagTuple,
                 recent_points: int, coarse_points: int,
                 boundaries=None):
        self.name = name
        self.kind = kind
        self.tags = tags
        self.recent: deque = deque(maxlen=recent_points)
        self.coarse: deque = deque(maxlen=coarse_points)
        self.last_coarse_ts = 0.0
        self.last_value: Any = None   # scalar, or merged hist vector
        self.merged: Any = None       # cluster-merged running value
        self.procs: set = set()
        self.boundaries = boundaries
        self.point_cost = (_POINT_COST if kind != "histogram"
                           else _POINT_COST + _HIST_SLOT_COST
                           * (len(boundaries or ()) + 3))

    def points(self) -> List[list]:
        """Coarse history spliced before the fine ring, oldest first."""
        if self.recent:
            head_ts = self.recent[0][0]
            out = [p for p in self.coarse if p[0] < head_ts]
            out.extend(self.recent)
            return out
        return list(self.coarse)

    def cost(self) -> int:
        return (_SERIES_BASE_COST
                + (len(self.recent) + len(self.coarse)) * self.point_cost)


class MetricsHistoryStore:
    """Head-side bounded time-series store over metrics push snapshots.

    Single-writer by construction (the head's event loop); a lock still
    guards mutation vs. the query paths for direct (test/tool) use.
    """

    def __init__(self, recent_points: int = 240,
                 coarse_points: int = 360,
                 coarse_interval_s: float = 30.0,
                 max_bytes: int = 16 * 1024 * 1024,
                 staleness_s: float = 15.0,
                 max_series_per_metric: int = 64):
        from ray_tpu.util.locks import make_lock

        self.recent_points = max(8, int(recent_points))
        self.coarse_points = max(8, int(coarse_points))
        self.coarse_interval_s = float(coarse_interval_s)
        self.max_bytes = int(max_bytes)
        self.staleness_s = float(staleness_s)
        self.max_series_per_metric = max(1, int(max_series_per_metric))
        self._lock = make_lock("metrics_history.MetricsHistoryStore._lock")
        #: (name, tags) -> _Series; ordered by last update (LRU evict).
        self._series: "OrderedDict[tuple, _Series]" = OrderedDict()
        #: metric name -> live series count (per-metric cap accounting).
        self._name_counts: Dict[str, int] = {}
        #: proc key -> {(name, tags): raw cumulative value} (counters /
        #: histograms; the diff baseline).
        self._proc_last: Dict[str, Dict[tuple, Any]] = {}
        self._proc_push_ts: Dict[str, float] = {}
        self.bytes_used = 0
        self.evictions = 0
        self.cap_evictions = 0

    # -- ingest ----------------------------------------------------------

    def ingest(self, proc: str, snapshot: Dict[str, dict],
               ts: Optional[float] = None) -> int:
        """Diff one process's push snapshot in; returns points appended."""
        now = time.time() if ts is None else float(ts)
        appended = 0
        with self._lock:
            known = proc in self._proc_last
            plast = self._proc_last.setdefault(proc, {})
            self._proc_push_ts[proc] = now
            for name, data in snapshot.items():
                if name == "_meta" or not isinstance(data, dict):
                    continue
                kind = data.get("type")
                if kind == "histogram":
                    bounds = data.get("boundaries") or []
                    for pairs, vec in data.get("hists", []):
                        appended += self._ingest_cumulative(
                            proc, plast, known, name, kind,
                            _tag_tuple(pairs), [float(x) for x in vec],
                            now, bounds)
                elif kind == "counter":
                    for pairs, value in data.get("values", []):
                        appended += self._ingest_cumulative(
                            proc, plast, known, name, kind,
                            _tag_tuple(pairs), float(value), now, None)
                elif kind == "gauge":
                    for pairs, value in data.get("values", []):
                        appended += self._ingest_gauge(
                            proc, name, _tag_tuple(pairs), float(value),
                            now)
            if self.bytes_used > self.max_bytes:
                self._evict(now)
        return appended

    def _get_series(self, name: str, kind: str, tags: TagTuple,
                    boundaries=None) -> _Series:
        key = (name, tags)
        s = self._series.get(key)
        if s is None:
            if self._name_counts.get(name, 0) >= \
                    self.max_series_per_metric:
                self._evict_one_of(name)
            s = self._series[key] = _Series(
                name, kind, tags, self.recent_points,
                self.coarse_points, boundaries)
            self._name_counts[name] = self._name_counts.get(name, 0) + 1
            self.bytes_used += _SERIES_BASE_COST
        else:
            self._series.move_to_end(key)
        return s

    def _drop_series(self, key: tuple, s: _Series) -> None:
        """Bookkeeping shared by both eviction paths."""
        self.bytes_used -= s.cost()
        n = self._name_counts.get(s.name, 0) - 1
        if n > 0:
            self._name_counts[s.name] = n
        else:
            self._name_counts.pop(s.name, None)

    def _evict_one_of(self, name: str) -> None:
        """Per-metric cardinality cap: evict the least-recently-updated
        series OF THIS METRIC so a tag explosion on one name cannot
        LRU-thrash every other metric out of the byte budget."""
        for key, s in self._series.items():
            if key[0] == name:
                del self._series[key]
                self._drop_series(key, s)
                self.cap_evictions += 1
                try:
                    from ray_tpu.util import telemetry

                    telemetry.inc(
                        "ray_tpu_metrics_history_series_capped_total", 1)
                except Exception:  # lint: allow-silent(cap accounting is best-effort; the cap itself already held)
                    pass
                return

    def _append(self, s: _Series, ts: float, value) -> None:
        rotated = len(s.recent) == s.recent.maxlen
        s.recent.append([ts, value])
        if not rotated:
            self.bytes_used += s.point_cost
        if ts - s.last_coarse_ts >= self.coarse_interval_s:
            s.last_coarse_ts = ts
            rotated = len(s.coarse) == s.coarse.maxlen
            s.coarse.append([ts, value])
            if not rotated:
                self.bytes_used += s.point_cost

    def _ingest_cumulative(self, proc: str, plast: dict, known: bool,
                           name: str, kind: str, tags: TagTuple,
                           value, ts: float, bounds) -> int:
        key = (name, tags)
        prev = plast.get(key)
        plast[key] = value
        if prev is None and not known:
            return 0  # first snapshot from this proc: seed only
        if kind == "histogram":
            if prev is None:
                delta = list(value)
            else:
                delta = [max(0.0, a - b) for a, b in zip(value, prev)]
                if value[-1] < prev[-1]:  # proc restart: counts reset
                    delta = list(value)
            if delta[-1] == 0 and sum(delta) == 0:
                return 0
            s = self._get_series(name, kind, tags, bounds)
            if s.merged is None:
                s.merged = [0.0] * len(delta)
                self._append(s, ts - 1e-3, list(s.merged))
            s.merged = [a + b for a, b in zip(s.merged, delta)]
            s.procs.add(proc)
            s.last_value = s.merged
            self._append(s, ts, list(s.merged))
            return 1
        # counter
        if prev is None:
            delta = value
        else:
            delta = value - prev
            if delta < 0:  # proc restart: counter reset
                delta = value
        if delta == 0:
            return 0
        s = self._get_series(name, kind, tags)
        if s.merged is None:
            s.merged = 0.0
            self._append(s, ts - 1e-3, 0.0)
        s.merged += delta
        s.procs.add(proc)
        s.last_value = s.merged
        self._append(s, ts, s.merged)
        return 1

    def _ingest_gauge(self, proc: str, name: str, tags: TagTuple,
                      value: float, ts: float) -> int:
        s = self._get_series(name, "gauge", tags)
        s.procs.add(proc)
        if s.last_value is not None and value == s.last_value:
            return 0
        s.last_value = value
        self._append(s, ts, value)
        return 1

    def _evict(self, now: float) -> None:
        """Drop least-recently-updated series until under the budget."""
        dropped = 0
        while self.bytes_used > self.max_bytes and len(self._series) > 1:
            key, s = self._series.popitem(last=False)
            self._drop_series(key, s)
            dropped += 1
        if not dropped:
            return
        self.evictions += dropped
        try:
            from ray_tpu.util import telemetry

            telemetry.inc("ray_tpu_metrics_history_evictions_total",
                          dropped)
        except Exception:  # lint: allow-silent(eviction accounting is best-effort; the cap itself already held)
            pass

    def on_proc_gone(self, proc: str) -> None:
        with self._lock:
            self._proc_last.pop(proc, None)
            self._proc_push_ts.pop(proc, None)
            for s in self._series.values():
                s.procs.discard(proc)

    # -- queries ---------------------------------------------------------

    def _fresh(self, s: _Series, now: float) -> bool:
        return any(self._proc_push_ts.get(p, 0.0)
                   >= now - self.staleness_s for p in s.procs)

    def _select(self, name: str, tags: Optional[Dict[str, str]]
                ) -> List[_Series]:
        return [s for (n, tt), s in self._series.items()
                if n == name and _tags_match(tt, tags)]

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def point_count(self) -> int:
        with self._lock:
            return sum(len(s.recent) + len(s.coarse)
                       for s in self._series.values())

    def index(self) -> List[dict]:
        """One row per live series: name, kind, tags, coverage."""
        now = time.time()
        with self._lock:
            out = []
            for (name, tt), s in self._series.items():
                pts = s.points()
                out.append({
                    "name": name, "kind": s.kind, "tags": dict(tt),
                    "points": len(pts),
                    "first_ts": pts[0][0] if pts else None,
                    "last_ts": pts[-1][0] if pts else None,
                    "fresh": self._fresh(s, now),
                })
            return out

    def query_points(self, name: str, window_s: float = 600.0,
                     now: Optional[float] = None,
                     tags: Optional[Dict[str, str]] = None,
                     max_points: Optional[int] = None) -> List[dict]:
        """Scalar point series per matching tag set (histograms render
        their cumulative observation count)."""
        now = time.time() if now is None else now
        cutoff = now - float(window_s)
        with self._lock:
            out = []
            for s in self._select(name, tags):
                pts = [[p[0], (p[1][-1] if s.kind == "histogram"
                               else p[1])]
                       for p in s.points() if p[0] >= cutoff]
                if max_points and len(pts) > max_points:
                    pts = pts[-max_points:]
                out.append({"tags": dict(s.tags), "kind": s.kind,
                            "points": pts,
                            "fresh": self._fresh(s, now)})
            return out

    def window_agg(self, name: str, agg: str, window_s: float,
                   now: Optional[float] = None,
                   tags: Optional[Dict[str, str]] = None) -> List[dict]:
        """One aggregate per matching series over the trailing window.

        counters: ``delta`` / ``rate`` / ``last``; gauges: ``last`` /
        ``max`` / ``min`` / ``avg`` (the last-known value carries
        forward while any writing process is still pushing — a constant
        gauge is current, a dead process's gauge is not); histograms:
        ``p50``/``p90``/``p95``/``p99`` over the window's bucket delta,
        plus ``delta``/``rate`` of the observation count.
        """
        now = time.time() if now is None else now
        window_s = float(window_s)
        cutoff = now - window_s
        with self._lock:
            out = []
            for s in self._select(name, tags):
                value = self._agg_one(s, agg, cutoff, now, window_s)
                if value is None:
                    continue
                out.append({"tags": dict(s.tags), "kind": s.kind,
                            "value": value})
            return out

    def _agg_one(self, s: _Series, agg: str, cutoff: float, now: float,
                 window_s: float) -> Optional[float]:
        pts = s.points()
        baseline = None
        window = []
        for p in pts:
            if p[0] < cutoff:
                baseline = p
            else:
                window.append(p)
        if s.kind == "gauge":
            vals = [p[1] for p in window]
            if self._fresh(s, now) and s.last_value is not None:
                vals.append(s.last_value)  # carry-forward while live
            if not vals:
                return None
            if agg in ("last", ""):
                return vals[-1]
            if agg == "max":
                return max(vals)
            if agg == "min":
                return min(vals)
            if agg == "avg":
                return sum(vals) / len(vals)
            raise ValueError(f"bad gauge agg {agg!r}")
        if not window:
            return None
        base = baseline if baseline is not None else window[0]
        last = window[-1]
        if s.kind == "counter":
            delta = last[1] - base[1]
            if agg == "delta":
                return delta
            if agg == "rate":
                return delta / window_s if window_s > 0 else 0.0
            if agg in ("last", ""):
                return last[1]
            raise ValueError(f"bad counter agg {agg!r}")
        # histogram
        base_vec = base[1]
        last_vec = last[1]
        if agg == "delta":
            return last_vec[-1] - base_vec[-1]
        if agg == "rate":
            return ((last_vec[-1] - base_vec[-1]) / window_s
                    if window_s > 0 else 0.0)
        if agg in ("p50", "p90", "p95", "p99"):
            q = float(agg[1:]) / 100.0
            nb = len(s.boundaries or [])
            deltas = [max(0.0, a - b) for a, b in
                      zip(last_vec[:nb + 1], base_vec[:nb + 1])]
            return _bucket_percentile(s.boundaries or [], deltas, q)
        raise ValueError(f"bad histogram agg {agg!r}")

    def snapshot(self, max_points: Optional[int] = 512) -> dict:
        """Full JSONable dump (debug bundles / bench artifacts)."""
        series = []
        now = time.time()
        with self._lock:
            for (name, tt), s in self._series.items():
                pts = s.points()
                if max_points and len(pts) > max_points:
                    pts = pts[-max_points:]
                series.append({
                    "name": name, "kind": s.kind, "tags": dict(tt),
                    "points": pts,
                    "fresh": self._fresh(s, now),
                    # Carried so restore() can rebuild histogram series
                    # with working percentile aggregation.
                    "boundaries": (list(s.boundaries)
                                   if s.boundaries else None),
                })
            return {
                "ts": now,
                "series_count": len(self._series),
                "point_count": sum(len(x["points"]) for x in series),
                "bytes": self.bytes_used,
                "max_bytes": self.max_bytes,
                "evictions": self.evictions,
                "cap_evictions": self.cap_evictions,
                "series": series,
            }


    def restore(self, snapshot: dict) -> int:
        """Rebuild series from a ``snapshot()`` dump (the head's
        experiment-state journal, reloaded on head restart); returns
        points restored. Existing series are preserved — restore is
        meant to run on an empty store before the first push.

        Per-proc cumulative baselines are deliberately NOT restored:
        after a head restart every process's next push re-seeds its
        baseline (first-snapshot rule) and subsequent deltas continue
        the restored merged value, so counters stay monotone across
        the restart instead of double-counting pre-restart totals."""
        restored = 0
        with self._lock:
            for row in snapshot.get("series", []):
                name, kind = row.get("name"), row.get("kind")
                pts = row.get("points") or []
                if not name or not kind or not pts:
                    continue
                tags = _tag_tuple((row.get("tags") or {}).items())
                if (name, tags) in self._series:
                    continue
                s = self._get_series(name, kind, tags,
                                     row.get("boundaries"))
                for ts, value in pts:
                    self._append(s, float(ts),
                                 (list(value) if kind == "histogram"
                                  else float(value)))
                    restored += 1
                last = pts[-1][1]
                s.last_value = (list(last) if kind == "histogram"
                                else float(last))
                if kind in ("counter", "histogram"):
                    s.merged = s.last_value
            if self.bytes_used > self.max_bytes:
                self._evict(time.time())
        return restored


def _bucket_percentile(boundaries: List[float], deltas: List[float],
                       q: float) -> Optional[float]:
    """Prometheus-style histogram_quantile over a windowed bucket
    delta vector (len(boundaries)+1 buckets, last = +Inf). Linear
    interpolation inside the bucket; the +Inf bucket clamps to the
    highest finite boundary."""
    total = sum(deltas)
    if total <= 0:
        return None
    rank = q * total
    acc = 0.0
    for i, count in enumerate(deltas):
        if count <= 0:
            continue
        if acc + count >= rank:
            lower = boundaries[i - 1] if i > 0 else 0.0
            if i >= len(boundaries):  # +Inf bucket
                return float(boundaries[-1]) if boundaries else 0.0
            upper = boundaries[i]
            return lower + (upper - lower) * ((rank - acc) / count)
        acc += count
    return float(boundaries[-1]) if boundaries else 0.0
