"""Live profiling plane: an in-process sampling profiler with
task/step attribution.

Reference: Ray's dashboard ships py-spy/memray capture buttons and
``ray stack`` as its "what is this worker doing RIGHT NOW" story
(dashboard/modules/reporter/profile_manager.py, arXiv:1712.05889);
TPU training work shows per-step timing attribution is what separates
"compile stall" from "collective stall" from "input starvation" when a
pjit program wedges (arXiv:2204.06514). This module is the
zero-dependency equivalent: a sampler thread reads
``sys._current_frames()`` at a configurable Hz and aggregates folded
stacks (``root;frame;frame`` → count, the flamegraph input format)
with bounded memory.

Two modes:

- **on-demand** — ``capture(duration_s, hz)`` samples for a bounded
  window and returns folded stacks + per-task attribution. The
  ``profile_capture`` RPC (CoreWorker / node agent) runs it off-loop;
  the head fans it out cluster-wide (``profile_capture_cluster``) for
  ``ray_tpu profile worker|task|actor|cluster`` and ``GET /profile``.
- **continuous** — ``maybe_start_continuous()`` starts an always-on
  low-Hz background sampler (config ``profiler_continuous_enabled``)
  that rewrites periodic folded snapshots into the session dir,
  publishes a ``profile:<pid>`` timeline lane, and self-checks its
  measured overhead against ``profiler_max_overhead_ratio`` (halving
  its rate when it overshoots — the profiler must never become the
  thing it profiles).

Attribution: executors publish what each thread is doing
(``push_thread_context(task=..., name=...)`` from the worker executor,
``serve_request=...`` from Serve replicas, step phases from the train
session) so every sampled stack lands under a ``task:<name>`` /
``serve:<deployment>`` root instead of an anonymous thread, and the
reply carries per-task sample buckets.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

#: Frames kept per sampled stack (deep jax traces otherwise dominate
#: the folded key space).
MAX_DEPTH = 48
#: Unique folded stacks retained per aggregation; the long tail folds
#: into OVERFLOW_KEY so a pathological workload can't grow memory
#: unboundedly.
MAX_UNIQUE_STACKS = 4096
OVERFLOW_KEY = "<overflow>"

# ---------------------------------------------------------------------------
# thread attribution registry
# ---------------------------------------------------------------------------

#: thread ident -> stack of label dicts. Only the owning thread mutates
#: its own list (GIL-atomic dict ops); the sampler reads racily and
#: tolerates a concurrent pop.
_thread_labels: Dict[int, List[dict]] = {}


def push_thread_context(**labels: Any) -> dict:
    """Publish what the current thread is executing (task id/name,
    serve request, ...). Returns a token for ``pop_thread_context`` —
    tokens (not LIFO order) make this safe for interleaved coroutines
    sharing one loop thread."""
    stack = _thread_labels.setdefault(threading.get_ident(), [])
    stack.append(labels)
    return labels


def pop_thread_context(token: Optional[dict] = None) -> None:
    stack = _thread_labels.get(threading.get_ident())
    if not stack:
        return
    if token is None:
        stack.pop()
        return
    try:
        stack.remove(token)
    except ValueError:  # lint: allow-silent(token already popped — benign double-clear)
        pass


def current_thread_context() -> Optional[dict]:
    stack = _thread_labels.get(threading.get_ident())
    return stack[-1] if stack else None


# ---------------------------------------------------------------------------
# sampling core
# ---------------------------------------------------------------------------

def _add(counts: Dict[str, int], key: str, n: int = 1) -> None:
    """Bounded folded-stack increment: beyond MAX_UNIQUE_STACKS new
    keys collapse into OVERFLOW_KEY (existing keys keep counting)."""
    if key in counts or len(counts) < MAX_UNIQUE_STACKS:
        counts[key] = counts.get(key, 0) + n
    else:
        counts[OVERFLOW_KEY] = counts.get(OVERFLOW_KEY, 0) + n


def _fold_frames(frame, max_depth: int = MAX_DEPTH) -> List[str]:
    frames: List[str] = []
    f = frame
    while f is not None and len(frames) < max_depth:
        code = f.f_code
        frames.append(
            f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}")
        f = f.f_back
    frames.reverse()
    return frames


def _sweep(counts: Dict[str, int], tasks: Dict[str, dict],
           skip_ident: Optional[int]) -> int:
    """Sample every live thread once into ``counts`` (folded) and
    ``tasks`` (per-task sample buckets). Returns samples taken."""
    thread_names = {t.ident: t.name for t in threading.enumerate()}
    n = 0
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        try:
            frames = _fold_frames(frame)
        except Exception:  # lint: allow-silent(frame freed mid-walk — skip one sample)
            continue
        label = None
        stack = _thread_labels.get(ident)
        if stack:
            try:
                label = stack[-1]
            except IndexError:  # lint: allow-silent(owner popped concurrently)
                label = None
        if label:
            bucket = label.get("task") or label.get("serve_request") or ""
            name = label.get("name") or bucket or "?"
            # Names that carry their own kind prefix (Serve pushes
            # "serve:<deployment>") keep it; plain task names get the
            # task: root.
            root = name if ":" in name else f"task:{name}"
            if bucket:
                entry = tasks.get(bucket)
                if entry is None and len(tasks) < 512:
                    entry = tasks[bucket] = dict(label, samples=0)
                if entry is not None:
                    entry["samples"] = entry.get("samples", 0) + 1
        else:
            root = f"thread:{thread_names.get(ident, ident)}"
        _add(counts, ";".join([root] + frames) if frames else root)
        n += 1
    return n


def capture(duration_s: float = 5.0, hz: float = 100.0) -> dict:
    """On-demand sampling window over every thread of THIS process.
    Blocks for ``duration_s`` (callers on an event loop must run it in
    an executor); returns folded stacks, per-task attribution buckets
    and the measured sampling-overhead ratio."""
    duration_s = min(max(float(duration_s), 0.05), 600.0)
    hz = min(max(float(hz), 1.0), 1000.0)
    interval = 1.0 / hz
    counts: Dict[str, int] = {}
    tasks: Dict[str, dict] = {}
    me = threading.get_ident()
    t0 = time.monotonic()
    deadline = t0 + duration_s
    sample_time = 0.0
    sweeps = 0
    samples = 0
    next_t = t0
    while time.monotonic() < deadline:
        s0 = time.perf_counter()
        samples += _sweep(counts, tasks, me)
        sample_time += time.perf_counter() - s0
        sweeps += 1
        next_t += interval
        delay = next_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        else:
            # Fell behind (slow sweep / busy host): re-anchor instead of
            # spiraling into a zero-sleep loop.
            next_t = time.monotonic()
    elapsed = max(time.monotonic() - t0, 1e-9)
    overhead = sample_time / elapsed
    from ray_tpu.util import flight_recorder, telemetry

    telemetry.inc("ray_tpu_profiler_samples_total", samples,
                  {"mode": "on_demand"})
    flight_recorder.record(
        "profile", "captured", sweeps=sweeps, samples=samples,
        duration_s=round(elapsed, 3), hz=hz,
        overhead=round(overhead, 5))
    return {
        "pid": os.getpid(),
        "ts": time.time(),
        "duration_s": round(elapsed, 4),
        "hz": hz,
        "sweeps": sweeps,
        "samples": samples,
        "overhead_ratio": round(overhead, 5),
        "folded": counts,
        "tasks": tasks,
    }


# ---------------------------------------------------------------------------
# folded-stack text + flamegraph HTML
# ---------------------------------------------------------------------------

def folded_text(folded: Dict[str, int]) -> str:
    """The standard ``stack count`` lines (flamegraph.pl / speedscope
    input), heaviest first."""
    lines = [f"{stack} {count}" for stack, count in
             sorted(folded.items(), key=lambda kv: -kv[1])]
    return "\n".join(lines) + ("\n" if lines else "")


def merge_folded(entries: List[dict]) -> Dict[str, int]:
    """Merge per-process capture replies into one folded dict, each
    stack rooted at its source (``worker:ab12...;task:f;...``)."""
    merged: Dict[str, int] = {}
    for entry in entries:
        source = entry.get("source") or f"pid:{entry.get('pid', '?')}"
        for stack, count in (entry.get("folded") or {}).items():
            _add(merged, f"{source};{stack}", count)
    return merged


def _tree(folded: Dict[str, int]) -> dict:
    root: dict = {"n": "all", "v": 0, "c": {}}
    for stack, count in folded.items():
        root["v"] += count
        node = root
        for part in stack.split(";"):
            child = node["c"].get(part)
            if child is None:
                child = node["c"][part] = {"n": part, "v": 0, "c": {}}
            child["v"] += count
            node = child
    def listify(node):
        node["c"] = sorted((listify(ch) for ch in node["c"].values()),
                           key=lambda ch: -ch["v"])
        return node
    return listify(root)


_FLAME_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>%(title)s</title><style>
body{font:12px monospace;margin:0;background:#1b1b1f;color:#ddd}
#hdr{padding:8px 12px;border-bottom:1px solid #333}
#fg{padding:8px 12px}
.row{white-space:nowrap;height:18px}
.fr{display:inline-block;height:16px;margin:1px 0 0 0;overflow:hidden;
 vertical-align:top;border-radius:2px;cursor:pointer;color:#1b1b1f;
 font-size:11px;padding:1px 0 0 3px;box-sizing:border-box}
.fr:hover{filter:brightness(1.2)}
#tip{padding:4px 12px;color:#9a9}
</style></head><body>
<div id="hdr">%(title)s &mdash; %(samples)s samples
 (click a frame to zoom, click the root to reset)</div>
<div id="fg"></div><div id="tip"></div>
<script>
var DATA=%(data)s;
function color(name){
 if(name.indexOf('task:')===0)return 'hsl(20,75%%,62%%)';
 if(name.indexOf('thread:')===0)return 'hsl(210,45%%,62%%)';
 if(name.indexOf('worker:')===0||name.indexOf('agent:')===0||
    name.indexOf('head')===0)return 'hsl(260,35%%,66%%)';
 var h=0;for(var i=0;i<name.length;i++)h=(h*31+name.charCodeAt(i))%%360;
 return 'hsl('+h+',55%%,60%%)';}
function render(root){
 var fg=document.getElementById('fg');fg.innerHTML='';
 var rows=[];
 (function walk(node,depth,off){
   if(!rows[depth])rows[depth]=[];
   rows[depth].push({n:node.n,v:node.v,off:off,node:node});
   var o=off;
   node.c.forEach(function(ch){walk(ch,depth+1,o);o+=ch.v;});
 })(root,0,0);
 var total=root.v||1;
 rows.forEach(function(row){
   var div=document.createElement('div');div.className='row';
   var cursor=0;
   row.forEach(function(f){
     var gap=(f.off-cursor)/total*100;
     if(gap>0){var sp=document.createElement('span');
       sp.className='fr';sp.style.width=gap+'%%';
       sp.style.visibility='hidden';div.appendChild(sp);}
     var w=f.v/total*100;
     var el=document.createElement('span');el.className='fr';
     el.style.width=w+'%%';el.style.background=color(f.n);
     el.textContent=w>2?f.n:'';
     el.title=f.n+' ('+f.v+' samples, '+(f.v/total*100).toFixed(1)+'%%)';
     el.onclick=function(){render(f.node===root?DATA:f.node);
       document.getElementById('tip').textContent=
         'zoom: '+f.n+' ('+f.v+' samples)';};
     div.appendChild(el);cursor=f.off+f.v;
   });
   fg.appendChild(div);
 });}
render(DATA);
</script></body></html>
"""


def flamegraph_html(folded: Dict[str, int],
                    title: str = "ray_tpu profile") -> str:
    """A self-contained (no external assets) icicle-flamegraph HTML
    page for a folded-stack dict. Title and frame names are attacker-
    influenced (dashboard query params, user task names) — escape them
    out of HTML/script contexts."""
    import html as _html

    tree = _tree(folded)
    # <-escape so a frame named "</script>" cannot terminate the
    # inline script block; the JS only ever assigns names via
    # textContent/title, so no further escaping is needed client-side.
    data = json.dumps(tree).replace("<", "\\u003c")
    return _FLAME_TEMPLATE % {
        "title": _html.escape(title),
        "samples": tree["v"],
        "data": data,
    }


# ---------------------------------------------------------------------------
# continuous mode
# ---------------------------------------------------------------------------

class ContinuousSampler(threading.Thread):
    """Always-on low-Hz sampler: aggregates folded stacks, rewrites a
    per-process snapshot file every ``snapshot_interval_s``, emits a
    ``profile:<pid>`` timeline lane and the overhead gauge, and halves
    its rate whenever the measured overhead crosses the configured
    bound."""

    def __init__(self, hz: Optional[float] = None,
                 snapshot_interval_s: Optional[float] = None,
                 out_dir: Optional[str] = None,
                 max_overhead: Optional[float] = None):
        super().__init__(daemon=True, name="rtpu-profiler")
        cfg = _config()
        if hz is None:
            hz = cfg.profiler_continuous_hz if cfg is not None else 10.0
        if snapshot_interval_s is None:
            snapshot_interval_s = (cfg.profiler_snapshot_interval_s
                                   if cfg is not None else 5.0)
        if max_overhead is None:
            max_overhead = (cfg.profiler_max_overhead_ratio
                            if cfg is not None else 0.02)
        self.hz = float(hz)
        self.snapshot_interval_s = float(snapshot_interval_s)
        self.max_overhead = float(max_overhead)
        self.out_dir = out_dir or _default_out_dir()
        self.counts: Dict[str, int] = {}
        self.tasks: Dict[str, dict] = {}
        self.total_samples = 0
        self.last_overhead_ratio = 0.0
        self.throttled = False
        self.snapshot_path = os.path.join(
            self.out_dir, f"profile-{os.getpid()}.folded")
        self._stop_event = threading.Event()

    def stop(self) -> None:
        self._stop_event.set()

    def run(self) -> None:
        from ray_tpu.util import telemetry

        interval = 1.0 / max(self.hz, 0.1)
        window_t0 = time.monotonic()
        window_sample_time = 0.0
        window_samples = 0
        me = threading.get_ident()
        while not self._stop_event.wait(interval):
            s0 = time.perf_counter()
            window_samples += _sweep(self.counts, self.tasks, me)
            window_sample_time += time.perf_counter() - s0
            now = time.monotonic()
            if now - window_t0 < self.snapshot_interval_s:
                continue
            elapsed = max(now - window_t0, 1e-9)
            self.last_overhead_ratio = window_sample_time / elapsed
            self.total_samples += window_samples
            self._snapshot(window_t0, elapsed, window_samples, telemetry)
            if (self.last_overhead_ratio > self.max_overhead
                    and interval < 2.0):
                # Overhead self-check: the continuous mode must stay
                # under its budget on any host — back off the rate
                # rather than trusting the configured Hz.
                interval *= 2.0
                self.throttled = True
            window_t0 = time.monotonic()
            window_sample_time = 0.0
            window_samples = 0

    def _top_stack(self) -> str:
        if not self.counts:
            return ""
        stack = max(self.counts.items(), key=lambda kv: kv[1])[0]
        return stack.rsplit(";", 1)[-1]

    def _snapshot(self, t0_mono: float, dur: float, samples: int,
                  telemetry) -> None:
        telemetry.inc("ray_tpu_profiler_samples_total", samples,
                      {"mode": "continuous"})
        telemetry.set_gauge("ray_tpu_profiler_overhead_ratio",
                            self.last_overhead_ratio,
                            {"proc": telemetry.proc_tag()})
        telemetry.event(
            f"profile:{os.getpid()}",
            self._top_stack() or "idle",
            ts=time.time() - dur, dur=dur,
            args={"samples": samples,
                  "overhead_ratio": round(self.last_overhead_ratio, 5),
                  "throttled": self.throttled})
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = self.snapshot_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(folded_text(self.counts))
            os.replace(tmp, self.snapshot_path)
            cfg = _config()
            if cfg is not None:
                # Retention: stale snapshots from dead pids (and
                # anything else that lands here) rotate out oldest
                # first, so a long soak can't fill the disk.
                rotate_dir(self.out_dir,
                           cfg.profiler_snapshot_max_files,
                           cfg.profiler_snapshot_max_bytes,
                           keep=(self.snapshot_path,))
        except OSError:  # lint: allow-silent(snapshot dir gone — sampler must not die)
            pass


def rotate_dir(path: str, max_files: int = 0, max_bytes: int = 0,
               keep=()) -> int:
    """Bound a snapshot/output directory: delete the OLDEST regular
    files (by mtime) once the file count or total bytes exceed the
    caps. A cap of 0 disables that bound; paths in ``keep`` (the file
    just written) are never deleted. Returns files removed. Shared by
    the continuous sampler's snapshot dir and the device-trace output
    dir — both accumulate per-process files with no other GC."""
    max_files = int(max_files or 0)
    max_bytes = int(max_bytes or 0)
    if max_files <= 0 and max_bytes <= 0:
        return 0
    keep = {os.path.abspath(p) for p in keep}
    entries = []
    try:
        with os.scandir(path) as it:
            for de in it:
                if not de.is_file(follow_symlinks=False):
                    continue
                if os.path.abspath(de.path) in keep:
                    continue
                st = de.stat(follow_symlinks=False)
                entries.append((st.st_mtime, st.st_size, de.path))
    except OSError:
        return 0
    entries.sort(reverse=True)  # newest first
    kept_files = len(keep)
    kept_bytes = 0
    removed = 0
    for mtime, size, fpath in entries:
        over = ((max_files and kept_files >= max_files)
                or (max_bytes and kept_bytes + size > max_bytes))
        if over:
            try:
                os.remove(fpath)
                removed += 1
            except OSError:  # lint: allow-silent(raced with another rotator/reader — the bound still converges)
                pass
        else:
            kept_files += 1
            kept_bytes += size
    return removed


_continuous: Optional[ContinuousSampler] = None
_continuous_lock = threading.Lock()


def _config():
    try:
        from ray_tpu.core.config import get_config

        return get_config()
    except Exception:  # config not bootstrapped (bare tools)
        return None


def _default_out_dir() -> str:
    base = os.environ.get("RAY_TPU_SESSION_DIR")
    if base:
        return os.path.join(base, "profile")
    import tempfile

    return os.path.join(tempfile.gettempdir(), "ray_tpu", "profile")


def continuous_enabled() -> bool:
    cfg = _config()
    if cfg is not None:
        return bool(cfg.profiler_continuous_enabled)
    return os.environ.get(
        "RAY_TPU_PROFILER_CONTINUOUS_ENABLED", "0").lower() in (
            "1", "true", "yes")


def maybe_start_continuous() -> Optional[ContinuousSampler]:
    """Start the per-process continuous sampler if configured on.
    Idempotent; called from every process entrypoint (worker, agent,
    head, driver)."""
    global _continuous
    if _continuous is not None:
        return _continuous
    if not continuous_enabled():
        return None
    with _continuous_lock:
        if _continuous is None:
            sampler = ContinuousSampler()
            sampler.start()
            _continuous = sampler
    return _continuous


def stop_continuous_for_testing() -> None:
    global _continuous
    with _continuous_lock:
        if _continuous is not None:
            _continuous.stop()
            _continuous = None


# ---------------------------------------------------------------------------
# driver-side veneer (cluster fan-out + file outputs)
# ---------------------------------------------------------------------------

def capture_cluster(kind: str = "all", ident: Optional[str] = None,
                    duration_s: float = 5.0, hz: float = 100.0) -> dict:
    """Fan ``profile_capture`` out over the cluster (head handler
    ``profile_capture_cluster``): ``kind`` targets one worker / the
    worker running a task / an actor's worker, or every process."""
    from ray_tpu.util.state import _call

    return _call("profile_capture_cluster", {
        "kind": kind,
        "id": (ident or "").lower(),
        "duration_s": duration_s,
        "hz": hz,
    })


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in name)


def write_profile_outputs(reply: dict, out_dir: str,
                          title: str = "ray_tpu profile") -> dict:
    """Write a capture-cluster reply as files: per-source
    ``<source>.folded`` + ``<source>.html``, one merged
    ``flamegraph.html``, and a ``profile.json`` manifest. Returns the
    manifest."""
    os.makedirs(out_dir, exist_ok=True)
    manifest: Dict[str, Any] = {"sources": [], "errors": {},
                                "samples": 0, "tasks": {}}
    entries = reply.get("entries", [])
    for entry in entries:
        source = entry.get("source", "unknown")
        safe = _sanitize(source)
        if entry.get("error"):
            manifest["errors"][safe] = entry["error"]
            continue
        manifest["sources"].append(source)
        manifest["samples"] += entry.get("samples", 0)
        for task_hex, bucket in (entry.get("tasks") or {}).items():
            manifest["tasks"][task_hex] = dict(bucket, source=source)
        folded = entry.get("folded") or {}
        with open(os.path.join(out_dir, f"{safe}.folded"), "w") as f:
            f.write(folded_text(folded))
        with open(os.path.join(out_dir, f"{safe}.html"), "w") as f:
            f.write(flamegraph_html(folded, title=f"{title} — {source}"))
    merged = merge_folded([e for e in entries if not e.get("error")])
    flame = os.path.join(out_dir, "flamegraph.html")
    with open(flame, "w") as f:
        f.write(flamegraph_html(merged, title=title))
    manifest["flamegraph"] = flame
    with open(os.path.join(out_dir, "profile.json"), "w") as f:
        json.dump(dict(manifest, reply_ts=reply.get("ts")), f, indent=1,
                  default=str)
    return manifest
