"""Runtime lockdep witness — the dynamic half of the concurrency lint
plane (the static half is ``ray_tpu/tools/analysis``).

Reference: the Linux kernel's lockdep validator and TSan's deadlock
detector — record the *order* in which each thread acquires named
locks, build the global acquired-while-holding graph, and report a
lock-order inversion the first time a cycle closes, i.e. **before** the
actual ABBA interleaving deadlocks a soak run.

Production cost is zero: ``make_lock(name)`` returns a plain
``threading.Lock``/``RLock`` unless the witness is enabled
(``RAY_TPU_LOCKDEP=1`` / config ``lockdep_enabled``, turned on by the
chaos/test lanes). When enabled, each acquisition does one thread-local
list walk plus a reachability probe over the (tiny) lock graph under a
single meta-lock; edges are deduplicated so the steady-state cost after
warm-up is a set lookup.

On detection: the cycle is recorded to the flight recorder
(``lockdep/inversion``) with both witness stacks, logged at ERROR, and
— in strict mode (``RAY_TPU_LOCKDEP_STRICT=1``, default in unit tests)
— raised as :class:`LockOrderInversion` so the test run fails at the
first bad ordering rather than at the eventual deadlock.
"""

from __future__ import annotations

import logging
import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "LockOrderInversion",
    "make_lock",
    "witness_enabled",
    "witness_graph",
    "reset_witness_for_testing",
]


class LockOrderInversion(RuntimeError):
    """Raised (strict mode) when acquiring a lock would close a cycle
    in the global acquired-while-holding graph."""


def witness_enabled() -> bool:
    """Whether new locks should be witness-instrumented. Checked once
    per ``make_lock`` call — existing locks keep whatever mode they
    were created with (the chaos/test lanes set the env var before the
    cluster comes up)."""
    raw = os.environ.get("RAY_TPU_LOCKDEP")
    if raw is not None:
        return raw.lower() not in ("0", "false", "no", "")
    try:
        from ray_tpu.core.config import get_config

        return bool(get_config().lockdep_enabled)
    except Exception:  # lint: allow-silent(config import cycle during bootstrap)
        return False


def _strict() -> bool:
    """Default is record-only: enabling the witness alone must never
    turn a survivable ordering bug into a crash. Tests and race-hunt
    lanes opt into raising with RAY_TPU_LOCKDEP_STRICT=1."""
    return os.environ.get("RAY_TPU_LOCKDEP_STRICT", "0").lower() in (
        "1", "true", "yes")


# ---------------------------------------------------------------------------
# the witness graph
# ---------------------------------------------------------------------------

# Edge A -> B means "some thread acquired B while holding A". A cycle
# means two threads can interleave into a deadlock. All three
# structures are guarded by _meta (never held while a witnessed lock's
# underlying primitive is being acquired — the probe runs before the
# blocking acquire).
_meta = threading.Lock()
_edges: Dict[str, Set[str]] = {}
_edge_stacks: Dict[Tuple[str, str], str] = {}
_reported: Set[Tuple[str, str]] = set()
_held = threading.local()


def _held_stack() -> List["WitnessLock"]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = []
        _held.stack = stack
    return stack


def _reachable(src: str, dst: str) -> Optional[List[str]]:
    """Path src -> ... -> dst over _edges (caller holds _meta), or None."""
    seen = {src}
    trail = [(src, [src])]
    while trail:
        node, path = trail.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                trail.append((nxt, path + [nxt]))
    return None


def witness_graph() -> Dict[str, List[str]]:
    """Snapshot of the acquired-while-holding graph (for debug dumps
    and tests)."""
    with _meta:
        return {a: sorted(bs) for a, bs in _edges.items()}


def reset_witness_for_testing() -> None:
    with _meta:
        _edges.clear()
        _edge_stacks.clear()
        _reported.clear()
    _held.stack = []


def _record_inversion(holding: str, acquiring: str, cycle: List[str],
                      prior_stack: str) -> None:
    here = "".join(traceback.format_stack(limit=12))
    pair = (holding, acquiring)
    with _meta:
        if pair in _reported:
            fresh = False
        else:
            _reported.add(pair)
            fresh = True
    if fresh:
        try:
            from ray_tpu.util import flight_recorder

            flight_recorder.record(
                "lockdep", "inversion", severity=flight_recorder.ERROR,
                holding=holding, acquiring=acquiring,
                cycle=" -> ".join(cycle + [cycle[0]]))
        except Exception:  # lint: allow-silent(witness must not crash the runtime)
            pass
        logger.error(
            "lock-order inversion: acquiring %r while holding %r closes "
            "cycle %s\nprior order witnessed at:\n%s\nthis order at:\n%s",
            acquiring, holding, " -> ".join(cycle + [cycle[0]]),
            prior_stack, here)


class WitnessLock:
    """A named lock that reports lock-order inversions at acquire time.

    Wraps a ``threading.Lock`` or ``RLock``; supports the context-
    manager protocol and explicit ``acquire``/``release``. Reentrant
    re-acquisition of an RLock does not add graph edges (it is not an
    ordering event)."""

    __slots__ = ("name", "_inner", "_reentrant")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    # -- witness core ---------------------------------------------------

    def _check_order(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        if any(held is self for held in stack):
            if self._reentrant:
                return
            # Re-acquiring a non-reentrant lock is a CERTAIN
            # self-deadlock — the inner acquire below would block on
            # ourselves forever. Always raise (even in record-only
            # mode): a witnessed exception beats a silent hang.
            try:
                from ray_tpu.util import flight_recorder

                flight_recorder.record(
                    "lockdep", "inversion",
                    severity=flight_recorder.ERROR,
                    holding=self.name, acquiring=self.name,
                    cycle=f"{self.name} -> {self.name}")
            except Exception:  # lint: allow-silent(witness must not crash the runtime)
                pass
            raise LockOrderInversion(
                f"re-acquiring non-reentrant lock {self.name!r} in the "
                f"same thread — certain self-deadlock")
        holder = stack[-1]
        with _meta:
            already = self.name in _edges.get(holder.name, ())
            if not already:
                # Adding holder->self: a cycle exists iff self already
                # reaches holder.
                cycle = _reachable(self.name, holder.name)
                _edges.setdefault(holder.name, set()).add(self.name)
                _edge_stacks[(holder.name, self.name)] = "".join(
                    traceback.format_stack(limit=12))
            else:
                cycle = None
            prior = _edge_stacks.get((self.name, holder.name), "")
        if cycle is not None:
            _record_inversion(holder.name, self.name,
                              [holder.name] + cycle[:-1], prior)
            if _strict():
                raise LockOrderInversion(
                    f"acquiring {self.name!r} while holding "
                    f"{holder.name!r} inverts the witnessed order "
                    f"{' -> '.join(cycle)}")

    # -- lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # Trylocks are exempt (as in kernel lockdep): a
            # non-blocking acquire can never deadlock, and a failed
            # one must not leave a phantom edge in the order graph.
            self._check_order()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held_stack().append(self)
        return got

    def release(self) -> None:
        stack = _held_stack()
        # Out-of-order release is legal for threading.Lock; drop the
        # newest matching entry.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked is not None else False

    def __repr__(self):
        return f"<WitnessLock {self.name!r}>"


def make_lock(name: str, reentrant: bool = False):
    """Factory used by the threaded subsystems (core_worker, router,
    object_store, retry, ...): a plain ``threading.Lock``/``RLock`` in
    production, a :class:`WitnessLock` when the lockdep lane is on. The
    ``name`` should be stable and globally unique-ish
    (``"module.Class.attr"``) — it is the node identity in the order
    graph."""
    if witness_enabled():
        return WitnessLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()
