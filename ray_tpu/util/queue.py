"""Distributed Queue (reference: python/ray/util/queue.py — an
actor-backed asyncio.Queue)."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        if timeout is None:
            await self.q.put(item)
            return True
        try:
            await asyncio.wait_for(self.q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return True, await self.q.get()
        try:
            return True, await asyncio.wait_for(self.q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item):
        try:
            self.q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self.q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def put_nowait_batch(self, items: List[Any]):
        ok = 0
        for item in items:
            try:
                self.q.put_nowait(item)
                ok += 1
            except asyncio.QueueFull:
                break
        return ok

    async def get_nowait_batch(self, num_items: int):
        out = []
        for _ in range(num_items):
            try:
                out.append(self.q.get_nowait())
            except asyncio.QueueEmpty:
                break
        return out

    async def qsize(self):
        return self.q.qsize()

    async def empty(self):
        return self.q.empty()

    async def full(self):
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, *,
                 actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None):
        if not block:
            ok = ray_tpu.get(self.actor.put_nowait.remote(item))
            if not ok:
                raise Full("queue full")
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout),
                         timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Full("queue full")

    def get(self, block: bool = True, timeout: Optional[float] = None):
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue empty")
            return item
        ok, item = ray_tpu.get(
            self.actor.get.remote(timeout),
            timeout=None if timeout is None else timeout + 30)
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> int:
        return ray_tpu.get(self.actor.put_nowait_batch.remote(list(items)))

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return ray_tpu.get(self.actor.get_nowait_batch.remote(num_items))

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
