"""ray_tpu — a TPU-native distributed AI runtime.

Task/actor/object core (reference capability: ray-project/ray) rebuilt
TPU-first: JAX/XLA/Pallas compute path, pod-slice-aware scheduling, GSPMD
parallelism presets, and a library stack (data, train, tune, serve, rllib)
on top.
"""

from ray_tpu._version import version as __version__
from ray_tpu import exceptions
from ray_tpu.api import (
    ActorClass,
    ActorHandle,
    ActorMethod,
    PlacementGroup,
    RemoteFunction,
    RuntimeContext,
    actor_exit,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    kv_del,
    kv_exists,
    kv_get,
    kv_keys,
    kv_put,
    list_named_actors,
    method,
    nodes,
    placement_group,
    put,
    remote,
    remove_placement_group,
    shutdown,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef

__all__ = [
    "ActorClass",
    "ActorHandle",
    "ActorMethod",
    "ObjectRef",
    "PlacementGroup",
    "RemoteFunction",
    "RuntimeContext",
    "__version__",
    "actor_exit",
    "available_resources",
    "cancel",
    "cluster_resources",
    "exceptions",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "kv_del",
    "kv_exists",
    "kv_get",
    "kv_keys",
    "kv_put",
    "list_named_actors",
    "method",
    "nodes",
    "placement_group",
    "put",
    "remote",
    "remove_placement_group",
    "shutdown",
    "wait",
]
