from ray_tpu.ops.attention import attention, flash_attention, reference_attention

__all__ = ["attention", "flash_attention", "reference_attention"]
