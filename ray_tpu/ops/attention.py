"""Attention kernels.

``flash_attention`` — Pallas TPU kernel with online softmax (blocked over
query and key/value tiles, accumulator carried in VMEM scratch across the
sequential kv grid dimension). Forward is the Pallas kernel; backward is an
XLA recompute VJP (full backward kernel is a later optimization).

The reference framework has no attention kernels at all (it defers to
torch); this is net-new TPU-first work (SURVEY.md §5.7) and the building
block the ring/Ulysses sequence parallelism in
``ray_tpu/parallel/ring_attention.py`` wraps.

Convention: q, k, v are (batch, seq, heads, head_dim); GQA is handled by
the caller broadcasting kv heads.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  sm_scale: float, causal: bool, block_q: int, block_k: int):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks); the k dimension is
    innermost (sequential on TPU) so scratch carries across it."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = True
    if causal:
        # Skip fully-masked kv blocks (strictly above the diagonal).
        run = ik * block_k <= (iq + 1) * block_q - 1

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (block_q, block_k)
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:]  # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k):
    batch, sq, heads, d = q.shape
    _, sk, _, _ = k.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must be multiples of blocks "
            f"({block_q},{block_k})"
        )
    # (B, S, H, D) -> (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(batch * heads, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(batch * heads, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(batch * heads, sk, d)

    from jax.experimental.pallas import tpu as pltpu

    interpret = jax.default_backend() == "cpu"
    grid = (batch * heads, sq // block_q, sk // block_k)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * heads, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(batch, heads, sq, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out)


def _flash_bwd(causal, sm_scale, block_q, block_k, residuals, g):
    """Blockwise (memory-efficient) backward: a lax.scan over key blocks
    with softmax statistics recomputed per block — never materializes
    the [B, H, S, S] score tensor, preserving the forward's O(S·block)
    memory property through training."""
    q, k, v, out = residuals
    batch, sq, heads, d = q.shape
    _, sk, _, _ = k.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    bk = min(block_k, sk)
    sk_pad = ((sk + bk - 1) // bk) * bk
    nk = sk_pad // bk

    # (B, S, H, D) -> (B*H, S, D), f32 accumulation.
    def flat(x):
        return (x.transpose(0, 2, 1, 3)
                .reshape(batch * heads, -1, x.shape[-1])
                .astype(jnp.float32))

    qf, kf, vf, of, gf = map(flat, (q, k, v, out, g))
    if sk_pad != sk:
        # Pad keys/values to a block multiple; padded positions are
        # masked out of the scores in both passes (k_pos >= sk). This
        # keeps memory O(S * block) for any length — a divisor-based
        # fallback degenerates to tiny blocks on prime lengths.
        pad = ((0, 0), (0, sk_pad - sk), (0, 0))
        kf = jnp.pad(kf, pad)
        vf = jnp.pad(vf, pad)
    q_pos = jnp.arange(sq)

    # delta_i = rowsum(dO_i * O_i)  (flash-attention bwd identity).
    delta = jnp.sum(of * gf, axis=-1)  # (BH, Sq)

    # Pass 1: recompute the log-sum-exp per query row, blockwise.
    def lse_step(carry, j):
        m_run, l_run = carry
        kb = jax.lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", qf, kb) * scale
        kp = j * bk + jnp.arange(bk)
        valid = kp < sk
        if causal:
            valid = valid[None, None, :] & (
                q_pos[None, :, None] >= kp[None, None, :])
        else:
            valid = jnp.broadcast_to(valid[None, None, :], s.shape)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_run, m_cur)
        l_run = (l_run * jnp.exp(m_run - m_new)
                 + jnp.sum(jnp.exp(s - m_new[..., None]), axis=-1))
        return (m_new, l_run), None

    bh = batch * heads
    (m_fin, l_fin), _ = jax.lax.scan(
        lse_step,
        (jnp.full((bh, sq), _NEG_INF, jnp.float32),
         jnp.zeros((bh, sq), jnp.float32)),
        jnp.arange(nk))
    lse = m_fin + jnp.log(jnp.maximum(l_fin, 1e-30))  # (BH, Sq)

    # Pass 2: accumulate dq; emit dk/dv per key block.
    def grad_step(dq_acc, j):
        kb = jax.lax.dynamic_slice_in_dim(kf, j * bk, bk, axis=1)
        vb = jax.lax.dynamic_slice_in_dim(vf, j * bk, bk, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", qf, kb) * scale
        kp = j * bk + jnp.arange(bk)
        valid = kp < sk
        if causal:
            valid = valid[None, None, :] & (
                q_pos[None, :, None] >= kp[None, None, :])
        else:
            valid = jnp.broadcast_to(valid[None, None, :], s.shape)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # (BH, Sq, bk)
        dv_j = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp = jnp.einsum("bqd,bkd->bqk", gf, vb)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, kb)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_acc, (dk_j, dv_j)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        grad_step, jnp.zeros_like(qf), jnp.arange(nk))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(bh, sk_pad, d)[:, :sk]
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(bh, sk_pad, d)[:, :sk]

    def unflat(x, dtype, s):
        return (x.reshape(batch, heads, s, d)
                .transpose(0, 2, 1, 3).astype(dtype))

    return (unflat(dq, q.dtype, sq), unflat(dk, k.dtype, sk),
            unflat(dv, v.dtype, sk))


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def reference_attention(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """Plain XLA attention (numerics reference + CPU/backward path)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
              impl: str = "auto"):
    """Dispatch between the Pallas flash kernel and the XLA reference.

    "auto": XLA for short sequences — measured on v5e, XLA's fused
    attention beats this flash kernel up to ~2k tokens (0.74s vs 1.0s
    per train step at seq 1024 in the bench model) — and flash beyond,
    where materializing the [B, H, S, S] score tensor stops fitting HBM
    and memory-linear streaming wins.
    """
    if impl == "auto":
        seq = q.shape[1]
        impl = ("flash" if jax.default_backend() == "tpu" and seq > 2048
                else "xla")
    if impl == "flash":
        return flash_attention(q, k, v, causal, sm_scale)
    return reference_attention(q, k, v, causal, sm_scale)
