"""Attention kernels.

``flash_attention`` — Pallas TPU kernels with online softmax (blocked over
query and key/value tiles, accumulators carried in VMEM scratch across the
sequential grid dimension). Forward saves the per-row log-sum-exp; the
backward is two blocked Pallas kernels (dk/dv accumulating over the query
grid, dq over the key/value grid — flash-attention paper alg. 2), so
neither pass ever materializes the [S, S] score tensor.

The reference framework has no attention kernels at all (it defers to
torch); this is net-new TPU-first work (SURVEY.md §5.7) and the building
block the ring/Ulysses sequence parallelism in
``ray_tpu/parallel/ring_attention.py`` wraps.

Convention: q, k, v are (batch, seq, heads, head_dim); GQA is handled by
the caller broadcasting kv heads.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30
_LANES = 128  # minor-dim tile for per-row stats (lse/delta)

# Tuned on v5e (train-mode sweep at seq 2048: 128/128 = 54.8ms,
# 256/256 = 26.6ms, 256/512 = 20.3ms — bigger tiles amortize the grid
# overhead and keep the MXU fed; VMEM comfortably fits the 512KB score
# tile).
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                  acc_ref, m_ref, l_ref, *,
                  sm_scale: float, causal: bool, block_q: int, block_k: int):
    """Grid: (batch*heads, num_q_blocks, num_k_blocks); the k dimension is
    innermost (sequential on TPU) so scratch carries across it."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = True
    if causal:
        # Skip fully-masked kv blocks (strictly above the diagonal).
        run = ik * block_k <= (iq + 1) * block_q - 1

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (block_q, block_k)
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:]  # (block_q, 1)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # Broadcast across a 128-lane minor dim (TPU block tiling
        # needs the last two dims (8,128)-aligned; same layout as
        # jax's reference flash kernel).
        lse_ref[0] = jnp.broadcast_to(m_ref[:] + jnp.log(denom),
                                      lse_ref.shape[1:])


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K):
    return _flash_forward(q, k, v, causal, sm_scale, block_q, block_k)[0]


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k):
    batch, sq, heads, d = q.shape
    _, sk, _, _ = k.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"seq lengths ({sq},{sk}) must be multiples of blocks "
            f"({block_q},{block_k})"
        )
    # (B, S, H, D) -> (B*H, S, D)
    qf = q.transpose(0, 2, 1, 3).reshape(batch * heads, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(batch * heads, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(batch * heads, sk, d)

    from jax.experimental.pallas import tpu as pltpu

    interpret = jax.default_backend() == "cpu"
    grid = (batch * heads, sq // block_q, sk // block_k)
    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch * heads, sq, d), q.dtype),
            jax.ShapeDtypeStruct((batch * heads, sq, _LANES),
                                 jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(batch, heads, sq, d).transpose(0, 2, 1, 3)
    # Keep one lane of the broadcast LSE: saving the (bh, sq, 128)
    # kernel layout as an AD residual would be 128x the data (64 MiB
    # per call in the bench config); the backward re-broadcasts.
    return out, lse[:, :, 0]


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                sm_scale: float, causal: bool,
                block_q: int, block_k: int):
    """dk/dv: grid (B*H, num_k_blocks, num_q_blocks); the q dimension is
    innermost (sequential) so the accumulators carry across it."""
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        # q blocks strictly above the diagonal contribute nothing.
        run = (iq + 1) * block_q - 1 >= ik * block_k

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)      # (bq, d)
        k = k_ref[0].astype(jnp.float32)      # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)    # (bq, d)
        lse = lse_ref[0][:, :1]               # (bq, 1)
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                  # (bq, bk)
        # dv += P^T dO
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dS = P * (dO V^T - delta)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        # dk += dS^T Q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, sm_scale: float, causal: bool,
               block_q: int, block_k: int):
    """dq: grid (B*H, num_q_blocks, num_k_blocks); kv innermost."""
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = ik * block_k <= (iq + 1) * block_q - 1

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]               # (bq, 1)
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(causal, sm_scale, block_q, block_k, residuals, g):
    """Blocked Pallas backward (flash-attention paper alg. 2): two
    kernels — dk/dv accumulating over the q grid, dq over the kv grid —
    using the forward's saved log-sum-exp; never materializes [S, S]."""
    q, k, v, out, lse = residuals
    batch, sq, heads, d = q.shape
    _, sk, _, _ = k.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(batch * heads, -1,
                                               x.shape[-1])

    qf, kf, vf, of, gf = map(flat, (q, k, v, out, g))
    bh = batch * heads
    # delta_i = rowsum(dO_i * O_i) (flash bwd identity) — tiny, XLA.
    delta = jnp.sum(of.astype(jnp.float32) * gf.astype(jnp.float32),
                    axis=-1)  # (BH, Sq)
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, _LANES))
    lse = jnp.broadcast_to(lse[..., None], (bh, sq, _LANES))

    from jax.experimental.pallas import tpu as pltpu

    interpret = jax.default_backend() == "cpu"
    nq, nk = sq // block_q, sk // block_k

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    def unflat(x, s):
        return x.reshape(batch, heads, s, d).transpose(0, 2, 1, 3)

    return unflat(dq, sq), unflat(dk, sk), unflat(dv, sk)


def _flash_bwd(causal, sm_scale, block_q, block_k, residuals, g):
    return _flash_bwd_pallas(causal, sm_scale, block_q, block_k,
                             residuals, g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def reference_attention(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None):
    """Plain XLA attention (numerics reference + CPU/backward path)."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
              impl: str = "auto"):
    """Dispatch between the Pallas flash kernels and the XLA reference.

    "auto": flash on TPU from 1024 tokens up — with the r5 blocked
    backward and 256/512 tiles it beats XLA's fused attention 1.24x at
    seq 1024 growing to 2.6x at 4096 (train-mode, BENCH_ATTN), and
    keeps O(S*block) memory where XLA OOMs (seq 8192 at 16GB HBM).
    XLA below 1024 (tiny sequences don't fill the tiles).
    """
    if impl == "auto":
        seq = q.shape[1]
        divisible = (seq % DEFAULT_BLOCK_Q == 0
                     and seq % DEFAULT_BLOCK_K == 0
                     and k.shape[1] % DEFAULT_BLOCK_K == 0)
        impl = ("flash" if jax.default_backend() == "tpu"
                and seq >= 1024 and divisible else "xla")
    if impl == "flash":
        return flash_attention(q, k, v, causal, sm_scale)
    return reference_attention(q, k, v, causal, sm_scale)
