"""Checker 1 — lock discipline.

Two invariants, both learned the hard way by every threaded runtime
(reference: Ray's C++ core runs whole-program TSan; the kernel runs
lockdep):

1. **No unbounded blocking while a lock is held.** A ``time.sleep``,
   ``subprocess.run``, timeout-less ``queue.get()`` / ``fut.result()``
   / ``proc.wait()`` inside a ``with <lock>:`` body turns every other
   thread that wants that lock into a hostage of the slow operation —
   and into a deadlock if the blocked-on work itself needs the lock.
   Detail key: ``blocking-under-lock: <call> [holding <lock>]``;
   pragma: ``# lint: allow-blocking(<reason>)``.

2. **Consistent lock acquisition order.** Every syntactic nesting
   ``with A: ... with B:`` contributes an edge A→B to a global
   acquired-while-holding graph; a cycle (including the trivial
   ``with A: ... with A:`` self-deadlock on a non-reentrant lock) is an
   ABBA inversion waiting for the right interleaving. Detail key:
   ``lock-order-cycle: A -> B -> A``; pragma:
   ``# lint: allow-lock-order(<reason>)`` on the edge site that closes
   the cycle.

Lock identification is syntactic: a ``with``/``async with`` context
expression whose dotted name contains ``lock`` (``self._lock``,
``_submit_lock``, ``member_lock`` ...). That convention holds across
this codebase and is cheap to keep true. Lock *identity* for the order
graph is ``<path>::<Class>.<dotted>`` so same-named attributes on
different classes stay distinct; the runtime witness
(``util/locks.py``) covers the orders static nesting can't see.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.analysis.common import (
    ContextVisitor,
    Violation,
    classify_blocking_call,
    collect_awaited_calls,
    dotted_name,
    suppressed,
)

CHECK = "lock-discipline"


def _lock_expr(item: ast.withitem) -> Optional[str]:
    name = dotted_name(item.context_expr)
    if name and "lock" in name.lower():
        return name
    return None


class _Visitor(ContextVisitor):
    def __init__(self, path: str, pragmas, awaited: Set[int]):
        super().__init__()
        self.path = path
        self.pragmas = pragmas
        self.awaited = awaited
        self.violations: List[Violation] = []
        # (holder, acquired) -> (line, context) of the first witness.
        self.edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
        self._held: List[Tuple[str, str]] = []  # (dotted, qualified id)
        self._class: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class.append(node.name)
        try:
            super().visit_ClassDef(node)
        finally:
            self._class.pop()

    def _lock_id(self, dotted: str) -> str:
        owner = self._class[-1] if self._class else "<module>"
        return f"{self.path}::{owner}.{dotted}"

    def _visit_with(self, node) -> None:
        acquired: List[Tuple[str, str]] = []
        for item in node.items:
            dotted = _lock_expr(item)
            if dotted is None:
                continue
            lock_id = self._lock_id(dotted)
            for _, held_id in self._held:
                self.edges.setdefault(
                    (held_id, lock_id), (node.lineno, self.context))
            acquired.append((dotted, lock_id))
        self._held.extend(acquired)
        try:
            self.generic_visit(node)
        finally:
            if acquired:
                del self._held[-len(acquired):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested def's body runs at call time, not while the lock is
        # syntactically held here.
        held, self._held = self._held, []
        try:
            super().visit_FunctionDef(node)
        finally:
            self._held = held

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        held, self._held = self._held, []
        try:
            super().visit_AsyncFunctionDef(node)
        finally:
            self._held = held

    def visit_Lambda(self, node: ast.Lambda) -> None:
        held, self._held = self._held, []
        try:
            self.generic_visit(node)
        finally:
            self._held = held

    def visit_Call(self, node: ast.Call) -> None:
        if self._held:
            detail = classify_blocking_call(node, self.awaited)
            if detail is not None and not suppressed(
                    self.pragmas, "blocking", node.lineno, node.lineno - 1):
                holder = self._held[-1][0]
                self.violations.append(Violation(
                    check=CHECK, path=self.path, line=node.lineno,
                    context=self.context,
                    detail=f"blocking-under-lock: {detail} "
                           f"[holding {holder}]"))
        self.generic_visit(node)


def check_module(path: str, tree: ast.AST, source: str, pragmas
                 ) -> Tuple[List[Violation],
                            Dict[Tuple[str, str], Tuple[str, int, str]]]:
    """Per-module pass: blocking-under-lock violations plus this
    module's lock-order edges ``{(holder, acquired): (path, line,
    context)}`` for the suite-wide cycle pass."""
    v = _Visitor(path, pragmas, collect_awaited_calls(tree))
    v.visit(tree)
    edges = {pair: (path, line, ctx)
             for pair, (line, ctx) in v.edges.items()}
    return v.violations, edges


def find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int, str]],
                pragmas_by_path: Dict[str, dict]) -> List[Violation]:
    """Cycle detection over the merged acquired-while-holding graph.
    Each cycle is reported once, at the witness site of its
    lexicographically-smallest edge, with a canonicalized detail key so
    the report is stable run-to-run."""
    graph: Dict[str, Set[str]] = {}
    for holder, acquired in edges:
        graph.setdefault(holder, set()).add(acquired)

    cycles: Set[Tuple[str, ...]] = set()

    def _walk(node: str, stack: List[str], on_stack: Set[str],
              done: Set[str]) -> None:
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cyc = stack[stack.index(nxt):]
                pivot = cyc.index(min(cyc))
                cycles.add(tuple(cyc[pivot:] + cyc[:pivot]))
            elif nxt not in done:
                _walk(nxt, stack, on_stack, done)
        stack.pop()
        on_stack.discard(node)
        done.add(node)

    visited: Set[str] = set()
    for root in sorted(graph):
        if root not in visited:
            _walk(root, [], set(), visited)

    def _short(lock_id: str) -> str:
        return lock_id.split("::", 1)[-1]

    out: List[Violation] = []
    for cyc in sorted(cycles):
        ring = list(cyc) + [cyc[0]]
        cycle_edges = sorted(zip(ring, ring[1:]))
        path, line, ctx = edges[cycle_edges[0]]
        if suppressed(pragmas_by_path.get(path, {}), "lock-order",
                      line, line - 1):
            continue
        out.append(Violation(
            check=CHECK, path=path, line=line, context=ctx,
            detail="lock-order-cycle: "
                   + " -> ".join(_short(l) for l in ring)))
    return out
