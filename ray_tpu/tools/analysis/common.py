"""Shared plumbing for the ``ray_tpu lint`` AST analyzers.

The suite (reference: Ray's ``ci/lint`` + ``bazel --config=tsan``
discipline, arxiv 1712.05889 §6) is repo-native: each checker knows
this codebase's concurrency invariants instead of generic style rules.
This module holds what every checker shares:

- :class:`Violation` — one finding, with a **line-stable identity key**
  ``check::path::context::detail`` (no line number) so the ratchet
  baseline survives unrelated edits that shift line numbers; the line
  is carried for humans only.
- the pragma grammar ``# lint: allow-<name>(<reason>)`` — a suppression
  must name the check family *and* give a non-empty reason; a reasonless
  pragma is ignored (the site stays flagged), so "why is this OK" is
  always in the diff.
- blocking-call classification shared by the lock-discipline and
  async-hygiene checkers.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

#: pragma names -> the checks they suppress (see each checker module).
PRAGMA_NAMES = ("silent", "blocking", "lock-order", "config")

_PRAGMA_RE = re.compile(
    r"#\s*lint:\s*allow-(?P<name>[a-z-]+)\(\s*(?P<reason>[^)]*?)\s*\)")


@dataclass(frozen=True)
class Violation:
    check: str      # checker id, e.g. "lock-discipline"
    path: str       # posix path relative to the scan root
    line: int       # 1-based; informational only, not part of identity
    context: str    # enclosing Class.method qualname or "<module>"
    detail: str     # stable description, e.g. "blocking-under-lock: time.sleep"

    @property
    def key(self) -> str:
        """Identity used by the ratchet baseline: everything except the
        line number, so touching unrelated code in a pinned file does
        not churn the baseline."""
        return "::".join((self.check, self.path, self.context, self.detail))

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.context}: {self.detail}"

    def to_dict(self) -> dict:
        return {"check": self.check, "path": self.path, "line": self.line,
                "context": self.context, "detail": self.detail,
                "key": self.key}


def collect_pragmas(source: str) -> Dict[int, Dict[str, str]]:
    """``{line: {pragma-name: reason}}`` for every well-formed
    ``# lint: allow-<name>(<reason>)`` in ``source``. Pragmas with an
    empty reason or an unknown name are dropped — the site stays
    flagged rather than silently suppressed by a typo."""
    out: Dict[int, Dict[str, str]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        for m in _PRAGMA_RE.finditer(text):
            name, reason = m.group("name"), m.group("reason")
            if name in PRAGMA_NAMES and reason:
                out.setdefault(lineno, {})[name] = reason
    return out


def suppressed(pragmas: Dict[int, Dict[str, str]], name: str,
               *lines: int) -> bool:
    """True when any of ``lines`` (a violation's own line, the line
    above it, a handler's body line, ...) carries an ``allow-<name>``
    pragma with a reason."""
    return any(name in pragmas.get(ln, ()) for ln in lines)


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ContextVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains ``self.context`` — the enclosing
    ``Class.method`` qualname (or ``"<module>"``) — while walking."""

    def __init__(self) -> None:
        self._ctx: List[str] = []

    @property
    def context(self) -> str:
        return ".".join(self._ctx) if self._ctx else "<module>"

    def _push_visit(self, node: ast.AST, name: str) -> None:
        self._ctx.append(name)
        try:
            self.generic_visit(node)
        finally:
            self._ctx.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._push_visit(node, node.name)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._push_visit(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._push_visit(node, node.name)


def _has_timeout(call: ast.Call) -> bool:
    """A positional arg or a ``timeout=`` kwarg counts as bounded."""
    if call.args:
        return True
    return any(kw.arg and "timeout" in kw.arg for kw in call.keywords)


def _queue_like(name: Optional[str]) -> bool:
    if not name:
        return False
    last = name.rsplit(".", 1)[-1].lower().strip("_")
    return last in ("q", "inq", "outq") or "queue" in last


#: subprocess entry points that block until the child exits (Popen
#: itself returns immediately and is classified by what follows it).
_SUBPROCESS_BLOCKING = {"run", "call", "check_call", "check_output"}


def classify_blocking_call(node: ast.Call,
                           awaited: Set[int]) -> Optional[str]:
    """Stable detail string when ``node`` is a call that can block the
    calling thread indefinitely, else None.

    ``awaited`` holds ``id()`` of Call nodes that are directly awaited —
    ``await q.get()`` is the asyncio (non-thread-blocking) form and is
    never flagged here.
    """
    if id(node) in awaited:
        return None
    func = node.func
    dotted = dotted_name(func)
    if dotted == "time.sleep":
        return "time.sleep"
    if dotted and dotted.startswith("subprocess."):
        tail = dotted.rsplit(".", 1)[-1]
        if tail in _SUBPROCESS_BLOCKING:
            return dotted
    if isinstance(func, ast.Attribute):
        recv = dotted_name(func.value)
        if func.attr == "get" and not node.args and not _has_timeout(node):
            # dict.get / ContextVar.get take or need no timeout; only a
            # queue-shaped receiver is an unbounded blocking get.
            if _queue_like(recv):
                return f"{recv}.get() without timeout"
        if func.attr == "result" and not _has_timeout(node):
            return (f"{recv or '<expr>'}.result() without timeout")
        if func.attr in ("communicate", "wait") and not _has_timeout(node):
            # subprocess.Popen.communicate/wait, threading.Event.wait.
            # str has neither method; asyncio's awaitable .wait() forms
            # are filtered by `awaited` above.
            return f"{recv or '<expr>'}.{func.attr}() without timeout"
        if func.attr == "join" and not node.args and not _has_timeout(node):
            # Zero-arg join is Thread/Process join (str.join takes an
            # iterable), unbounded without a timeout.
            return f"{recv or '<expr>'}.join() without timeout"
    return None


#: asyncio combinators whose call arguments are coroutines/awaitables —
#: ``asyncio.wait_for(q.get(), t)`` schedules q.get() cooperatively.
_ASYNC_WRAPPERS = {"wait_for", "gather", "wait", "shield", "create_task",
                   "ensure_future", "run_coroutine_threadsafe"}


def collect_awaited_calls(tree: ast.AST) -> Set[int]:
    """``id()`` of every Call node that is the direct operand of an
    ``await`` or an argument to an asyncio combinator
    (``wait_for``/``gather``/``create_task``/...) — those run
    cooperatively, not thread-blocking."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.rsplit(".", 1)[-1] in _ASYNC_WRAPPERS:
                for arg in node.args:
                    if isinstance(arg, ast.Call):
                        out.add(id(arg))
    return out
