"""``ray_tpu lint`` — the concurrency lint plane's static half.

Four repo-native AST checkers (lock discipline, async hygiene,
swallowed-exception audit, config-flag lint) with a ratcheted violation
baseline; the dynamic half is the lockdep witness in
``ray_tpu/util/locks.py`` and the TSan lane in ``cpp/tpustore``.

Entry points::

    ray_tpu lint [--json] [--update-baseline] [paths...]
    python -m ray_tpu.tools.analysis.runner
    tests/test_lint.py   (tier-1 ratchet gate)
"""

from ray_tpu.tools.analysis.common import (  # noqa: F401
    PRAGMA_NAMES,
    Violation,
    collect_pragmas,
)
