"""Checker 2 — async hygiene.

A blocking call inside an ``async def`` body stalls the whole event
loop, not just one request: the Serve proxy/router/replica planes and
the core RPC pump each multiplex hundreds of requests per loop, so one
``time.sleep`` or timeout-less ``fut.result()`` inside a handler is a
cluster-visible latency cliff (reference: Ray Serve forbids the same —
its replicas run user code off-loop for exactly this reason).

Flags direct, non-awaited blocking calls (`time.sleep`,
``subprocess.run``-family, timeout-less queue ``get`` / future
``result`` / ``communicate`` / ``wait`` / zero-arg ``join``) in the
body of every ``async def``. Nested synchronous ``def``s reset the
scope — they execute wherever they are *called* (often a thread-pool
executor), which is the sanctioned escape hatch.

Detail key: ``blocking-in-async: <call>``; pragma:
``# lint: allow-blocking(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.tools.analysis.common import (
    ContextVisitor,
    Violation,
    classify_blocking_call,
    collect_awaited_calls,
    suppressed,
)

CHECK = "async-hygiene"


class _Visitor(ContextVisitor):
    def __init__(self, path: str, pragmas, awaited):
        super().__init__()
        self.path = path
        self.pragmas = pragmas
        self.awaited = awaited
        self.violations: List[Violation] = []
        self._async_depth = 0

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._async_depth += 1
        try:
            super().visit_AsyncFunctionDef(node)
        finally:
            self._async_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A sync def nested in an async def runs at its call site (an
        # executor, a callback) — out of scope here.
        depth, self._async_depth = self._async_depth, 0
        try:
            super().visit_FunctionDef(node)
        finally:
            self._async_depth = depth

    def visit_Lambda(self, node: ast.Lambda) -> None:
        depth, self._async_depth = self._async_depth, 0
        try:
            self.generic_visit(node)
        finally:
            self._async_depth = depth

    def visit_Call(self, node: ast.Call) -> None:
        if self._async_depth > 0:
            detail = classify_blocking_call(node, self.awaited)
            if detail is not None and not suppressed(
                    self.pragmas, "blocking", node.lineno, node.lineno - 1):
                self.violations.append(Violation(
                    check=CHECK, path=self.path, line=node.lineno,
                    context=self.context,
                    detail=f"blocking-in-async: {detail}"))
        self.generic_visit(node)


def check_module(path: str, tree: ast.AST, source: str,
                 pragmas) -> List[Violation]:
    v = _Visitor(path, pragmas, collect_awaited_calls(tree))
    v.visit(tree)
    return v.violations
