"""Checker 4 — config-flag lint.

The reference keeps its 219-entry ``RAY_CONFIG`` table honest with an
X-macro: a flag cannot be read without being declared, and an
undeclared read is a compile error (ray_config_def.h). Python gives us
neither, so this checker closes both directions over
``core/config.py``'s ``Config`` dataclass:

1. **Undeclared read** — ``get_config().foo`` (or ``cfg.foo`` where
   ``cfg`` was provably bound from ``get_config()`` in the same scope,
   or a parameter annotated ``Config``) for a ``foo`` that is not a
   declared field. At runtime this raises ``AttributeError`` only on
   the code path that reads it — i.e. in production, at 3am. Detail:
   ``undeclared-config-read: <attr>``; pragma:
   ``# lint: allow-config(<reason>)``.

2. **Unread field** — a declared field no code reads is either dead
   (delete it) or a flag someone *believes* is wired in but isn't,
   which is worse. Read collection is deliberately liberal (any
   attribute read whose name matches a declared field, anywhere) so
   this direction has no false positives from aliasing through helper
   parameters. Reported at the field's declaration line in config.py;
   detail: ``unread-config-field: <name>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.analysis.common import (
    ContextVisitor,
    Violation,
    dotted_name,
    suppressed,
)

CHECK = "config-flag"

#: non-field attributes that are legal on a Config instance.
_CONFIG_METHODS = {"apply_system_config"}


def declared_fields(config_source: str) -> Dict[str, int]:
    """``{field name: declaration line}`` from the ``Config`` dataclass."""
    tree = ast.parse(config_source)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {
                stmt.target.id: stmt.lineno
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return {}


def _is_get_config_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return bool(name) and name.rsplit(".", 1)[-1] == "get_config"


def _is_config_annotation(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    name = dotted_name(ann)
    if name and name.rsplit(".", 1)[-1] == "Config":
        return True
    # "Config" as a string / Optional[Config] forward reference.
    return isinstance(ann, ast.Constant) and ann.value == "Config"


class _Visitor(ContextVisitor):
    def __init__(self, path: str, pragmas, fields: Set[str]):
        super().__init__()
        self.path = path
        self.pragmas = pragmas
        self.fields = fields
        self.violations: List[Violation] = []
        self.reads: Set[str] = set()
        # Stack of per-scope sets of names provably bound to the global
        # Config (assigned from get_config() or annotated Config).
        self._scopes: List[Set[str]] = [set()]

    # -- scope handling --------------------------------------------------

    def _function_scope(self, node) -> None:
        scope: Set[str] = set()
        args = node.args
        for arg in (list(args.posonlyargs) + list(args.args)
                    + list(args.kwonlyargs)):
            if _is_config_annotation(arg.annotation):
                scope.add(arg.arg)
        self._scopes.append(scope)
        try:
            self._push_visit(node, node.name)
        finally:
            self._scopes.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._function_scope(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._function_scope(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_get_config_call(node.value):
            for target in node.targets:
                name = dotted_name(target)
                if name:
                    self._scopes[-1].add(name)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        name = dotted_name(node.target)
        if name and (_is_get_config_call(node.value)
                     or _is_config_annotation(node.annotation)):
            self._scopes[-1].add(name)
        self.generic_visit(node)

    def _is_config_expr(self, node: ast.AST) -> bool:
        if _is_get_config_call(node):
            return True
        name = dotted_name(node)
        return bool(name) and any(name in s for s in self._scopes)

    # -- reads -----------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            if node.attr in self.fields:
                self.reads.add(node.attr)
            elif (not node.attr.startswith("_")
                    and node.attr not in _CONFIG_METHODS
                    and self._is_config_expr(node.value)
                    and not suppressed(self.pragmas, "config",
                                       node.lineno, node.lineno - 1)):
                self.violations.append(Violation(
                    check=CHECK, path=self.path, line=node.lineno,
                    context=self.context,
                    detail=f"undeclared-config-read: {node.attr}"))
        self.generic_visit(node)


def check_module(path: str, tree: ast.AST, source: str, pragmas,
                 fields: Dict[str, int]
                 ) -> Tuple[List[Violation], Set[str]]:
    """Per-module pass: undeclared-read violations plus the set of
    field names this module reads (for the suite-wide unread pass)."""
    v = _Visitor(path, pragmas, set(fields))
    v.visit(tree)
    return v.violations, v.reads


def find_unread(fields: Dict[str, int], reads: Set[str],
                config_path: str, pragmas_for_config: dict
                ) -> List[Violation]:
    out: List[Violation] = []
    for name, line in sorted(fields.items()):
        if name in reads:
            continue
        if suppressed(pragmas_for_config, "config", line, line - 1):
            continue
        out.append(Violation(
            check=CHECK, path=config_path, line=line, context="Config",
            detail=f"unread-config-field: {name}"))
    return out
