"""Checker 3 — swallowed-exception audit.

``except Exception: pass`` is how a distributed runtime loses its
evidence: the flight recorder (PR 4) can only explain a stall from the
events the code bothered to record, and a silent catch is an event
that never happened. The audit's contract: every handler whose body is
*only* ``pass`` (or ``...``) must either grow a real action — record
to the flight recorder (``guard/swallowed``), log, re-raise — or carry
an explicit ``# lint: allow-silent(<reason>)`` pragma stating why
dropping the error is correct (e.g. best-effort kill of an already-
exiting process).

Detail key: ``silent-except`` (+ the guarded exception type when it is
a simple name, so two handlers in one function stay distinct only if
they guard different types); pragma on the ``except`` line, the line
above it, or the ``pass`` line itself.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu.tools.analysis.common import (
    ContextVisitor,
    Violation,
    dotted_name,
    suppressed,
)

CHECK = "silent-except"


def _body_is_silent(body: List[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


class _Visitor(ContextVisitor):
    def __init__(self, path: str, pragmas):
        super().__init__()
        self.path = path
        self.pragmas = pragmas
        self.violations: List[Violation] = []

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if _body_is_silent(node.body):
            lines = [node.lineno, node.lineno - 1]
            if node.body:
                lines.append(node.body[0].lineno)
            if not suppressed(self.pragmas, "silent", *lines):
                guarded = dotted_name(node.type) if node.type else "bare"
                self.violations.append(Violation(
                    check=CHECK, path=self.path, line=node.lineno,
                    context=self.context,
                    detail=f"silent-except: {guarded or 'bare'}"))
        self.generic_visit(node)


def check_module(path: str, tree: ast.AST, source: str,
                 pragmas) -> List[Violation]:
    v = _Visitor(path, pragmas)
    v.visit(tree)
    return v.violations
