"""The ``ray_tpu lint`` driver: walk the package, run the four
checkers, compare against the ratchet baseline.

Ratchet semantics (reference: the "burn-down file" pattern used by
large TSan/clang-tidy rollouts): ``baseline.json`` pins every
*pre-existing* violation by its line-stable key. A run fails when

- a violation appears whose key is not in the baseline (or whose count
  at that key grew) — **new debt is rejected**, or
- a baseline entry no longer fires — the fix must be banked with
  ``ray_tpu lint --update-baseline`` so the pin can't quietly regress
  back; **the baseline only shrinks**.

``--json`` emits the machine form for CI; exit code 0 means clean
modulo baseline AND no stale pins.
"""

from __future__ import annotations

import ast
import json
import os
import sys
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu.tools.analysis import (
    async_hygiene,
    config_flags,
    lock_discipline,
    silent_except,
)
from ray_tpu.tools.analysis.common import Violation, collect_pragmas

CHECKS = (lock_discipline.CHECK, async_hygiene.CHECK,
          silent_except.CHECK, config_flags.CHECK)

_SKIP_DIRS = {"__pycache__", ".git", "build"}


def package_root() -> str:
    """The ``ray_tpu`` package directory (default scan root)."""
    import ray_tpu

    return os.path.dirname(os.path.abspath(ray_tpu.__file__))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def iter_sources(root: str, paths: Optional[Iterable[str]] = None
                 ) -> Iterable[Tuple[str, str]]:
    """Yield ``(relative posix path, source)`` for every ``*.py`` under
    ``root`` (or just ``paths``, given relative to ``root``)."""
    if paths:
        files = [os.path.join(root, p) for p in paths]
    else:
        files = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                yield rel, f.read()
        except OSError as e:
            print(f"lint: cannot read {rel}: {e}", file=sys.stderr)


def run_lint(root: Optional[str] = None,
             paths: Optional[Iterable[str]] = None,
             config_source: Optional[str] = None) -> List[Violation]:
    """Run all four checkers; returns violations sorted by
    (path, line). ``config_source`` overrides the ``Config`` dataclass
    source for the config-flag checker (tests inject fixtures)."""
    root = root or package_root()
    config_rel = "core/config.py"
    if config_source is None:
        config_path = os.path.join(root, config_rel)
        if os.path.exists(config_path):
            with open(config_path, encoding="utf-8") as f:
                config_source = f.read()
        else:
            config_source = ""
    fields = config_flags.declared_fields(config_source)

    violations: List[Violation] = []
    all_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    all_reads = set()
    pragmas_by_path: Dict[str, dict] = {}
    config_pragmas: dict = {}

    for rel, source in iter_sources(root, paths):
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            violations.append(Violation(
                check="parse", path=rel, line=e.lineno or 0,
                context="<module>", detail=f"syntax-error: {e.msg}"))
            continue
        pragmas = collect_pragmas(source)
        pragmas_by_path[rel] = pragmas
        if rel == config_rel:
            config_pragmas = pragmas

        lock_v, edges = lock_discipline.check_module(
            rel, tree, source, pragmas)
        violations.extend(lock_v)
        for pair, site in edges.items():
            all_edges.setdefault(pair, site)

        violations.extend(async_hygiene.check_module(
            rel, tree, source, pragmas))
        violations.extend(silent_except.check_module(
            rel, tree, source, pragmas))
        if fields:
            cfg_v, reads = config_flags.check_module(
                rel, tree, source, pragmas, fields)
            violations.extend(cfg_v)
            all_reads.update(reads)

    violations.extend(lock_discipline.find_cycles(
        all_edges, pragmas_by_path))
    if fields:
        violations.extend(config_flags.find_unread(
            fields, all_reads, config_rel,
            config_pragmas or collect_pragmas(config_source)))
    return sorted(violations, key=lambda v: (v.path, v.line, v.check,
                                             v.detail))


# ---------------------------------------------------------------------------
# ratchet baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, dict]:
    """``{key: {"count": n, "lines": [...]}}`` or empty when absent."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    if data.get("version") != 1:
        raise ValueError(f"unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return data.get("entries", {})


def write_baseline(violations: List[Violation], path: str) -> dict:
    entries: Dict[str, dict] = {}
    for v in violations:
        row = entries.setdefault(v.key, {"count": 0, "lines": []})
        row["count"] += 1
        row["lines"].append(v.line)
    payload = {
        "version": 1,
        "tool": "ray_tpu lint --update-baseline",
        "total": len(violations),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return payload


def compare(violations: List[Violation], baseline: Dict[str, dict]
            ) -> Tuple[List[Violation], List[str]]:
    """``(new, stale)``: violations beyond the baseline's pinned count
    per key, and baseline keys whose pinned count exceeds what still
    fires (fixed debt that must be banked with --update-baseline)."""
    observed = Counter(v.key for v in violations)
    new: List[Violation] = []
    budget = {k: row.get("count", 0) for k, row in baseline.items()}
    for v in violations:
        if budget.get(v.key, 0) > 0:
            budget[v.key] -= 1
        else:
            new.append(v)
    stale = sorted(k for k, row in baseline.items()
                   if observed.get(k, 0) < row.get("count", 0))
    return new, stale


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="ray_tpu lint",
        description="repo-native concurrency/static analysis suite")
    p.add_argument("paths", nargs="*",
                   help="files relative to the package root "
                        "(default: the whole ray_tpu package)")
    p.add_argument("--root", default=None,
                   help="scan root (default: the installed ray_tpu "
                        "package directory)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--baseline", default=None,
                   help="ratchet baseline path (default: "
                        "tools/analysis/baseline.json); 'none' disables")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from this run's findings")
    args = p.parse_args(argv)

    violations = run_lint(root=args.root, paths=args.paths or None)

    baseline_path = args.baseline or default_baseline_path()
    use_baseline = baseline_path != "none" and not args.paths
    if args.update_baseline:
        if args.paths:
            # A partial scan would overwrite the whole baseline with
            # just these files' findings, silently unpinning the rest.
            print("lint: --update-baseline requires a full scan "
                  "(drop the path arguments)", file=sys.stderr)
            return 2
        if baseline_path == "none":
            print("lint: --update-baseline conflicts with "
                  "--baseline none", file=sys.stderr)
            return 2
        payload = write_baseline(violations, baseline_path)
        print(f"baseline updated: {payload['total']} violations across "
              f"{len(payload['entries'])} keys -> {baseline_path}")
        return 0

    baseline = load_baseline(baseline_path) if use_baseline else {}
    new, stale = compare(violations, baseline)

    if args.as_json:
        print(json.dumps({
            "total": len(violations),
            "baselined": len(violations) - len(new),
            "new": [v.to_dict() for v in new],
            "stale_baseline_keys": stale,
            "violations": [v.to_dict() for v in violations],
            "ok": not new and not stale,
        }, indent=1))
        return 0 if not new and not stale else 1

    by_check = Counter(v.check for v in violations)
    for v in new:
        print(v.render())
    summary = ", ".join(f"{c}: {by_check.get(c, 0)}" for c in CHECKS)
    print(f"lint: {len(violations)} total ({summary}); "
          f"{len(violations) - len(new)} baselined, {len(new)} new")
    if stale:
        print("lint: stale baseline entries (the debt was paid — bank "
              "it with `ray_tpu lint --update-baseline`):")
        for key in stale:
            print(f"  {key}")
    return 0 if not new and not stale else 1


if __name__ == "__main__":
    sys.exit(main())
