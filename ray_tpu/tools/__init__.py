"""Developer-facing tooling that ships inside the package (static
analysis, maintenance scripts). Nothing here is imported by the
runtime's hot paths."""
