"""Head service — the cluster control plane (GCS equivalent).

Reference: src/ray/gcs/gcs_server/gcs_server.h:78 composes actor / node /
job / placement-group managers, internal KV, pubsub and health checking;
this module is the same composition on one asyncio loop:

- worker/node registry + death detection (conn close ≈ health check fail)
- lease scheduling (delegates to ClusterScheduler / WorkerPool)
- actor manager with restarts (reference: gcs_actor_manager.cc:255,641,1326)
- placement groups (reference: gcs_placement_group_mgr)
- internal KV (reference: gcs_kv_manager.cc) — function table, named actors
- pubsub channels (reference: src/ray/pubsub/) — actor/node state, logs
- object directory for the node-wide shm store (seal events + waiters)
- task-event store for the state API (reference: gcs_task_manager)

All handlers run on the head's event loop; peers are either remote
``rpc.Connection``s (worker processes, remote drivers) or the in-process
driver's ``LocalPeer``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque
from typing import Dict, List, Optional, Set

from ray_tpu.core import object_transfer, retry, serialization
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, WorkerID
from ray_tpu.core.object_store import ShmStore
from ray_tpu.core.resources import ResourceSet
from ray_tpu.core.scheduler import (
    ClusterScheduler,
    Node,
    PendingLease,
    WorkerHandle,
    WorkerPool,
)
from ray_tpu.core.task_spec import ActorInfo, Bundle, NodeInfo, PlacementGroupInfo, TaskSpec

logger = logging.getLogger(__name__)


def _swallow(site: str, error: BaseException, **tags) -> None:
    """Evidence for intentionally-dropped errors (silent-except audit):
    ride the flight recorder (guard/swallowed) so the head's ``debug
    dump`` can explain them later."""
    from ray_tpu.util import flight_recorder

    flight_recorder.swallow(site, error, **tags)


def _stamp_caller(conn, kind: str) -> None:
    """Record the caller kind on the connection so the server-side RPC
    accounting (util/rpc_stats.py) attributes this peer's subsequent
    calls to worker/agent/driver instead of the generic fallback."""
    state = getattr(conn, "state", None)
    if isinstance(state, dict):
        state["caller_kind"] = kind


def _payload_nbytes(data) -> int:
    """Approximate wire size of one pubsub payload (the per-subscriber
    cost a publish multiplies)."""
    try:
        import msgpack

        return len(msgpack.packb(data, use_bin_type=True))
    except Exception:  # lint: allow-silent(size estimate only; non-msgpack-native payloads still publish)
        return 0


class HeadService:
    def __init__(self, config: Config, shm_store: ShmStore, session_dir: str,
                 host: str = "127.0.0.1", storage=None):
        self.config = config
        self.shm = shm_store
        self.session_dir = session_dir
        self.host = host
        # Durable backing store (gcs_storage.GcsStorage) — None disables
        # persistence (reference: in-memory store_client fallback).
        self.storage = storage
        self.port: Optional[int] = None
        self.pool: Optional[WorkerPool] = None
        self.scheduler: Optional[ClusterScheduler] = None

        self.kv: Dict[str, Dict[bytes, bytes]] = {}  # namespace -> {k: v}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[tuple, ActorID] = {}  # (namespace, name) -> id
        self.jobs: Dict[JobID, dict] = {}
        self._job_counter = 0
        self.nodes_info: Dict[NodeID, NodeInfo] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self._pg_waiters: Dict[PlacementGroupID, List[asyncio.Future]] = {}
        # pubsub: channel -> set of peers
        self.subscribers: Dict[str, Set] = {}
        # object directory: hex id -> size (sealed objects, cluster-wide)
        self.sealed_objects: Dict[str, int] = {}
        # hex id -> node ids holding a copy (reference:
        # ownership_based_object_directory.h location sets)
        self.object_locations: Dict[str, Set[NodeID]] = {}
        # Device-native object plane: hex id -> {"manifest": [leaf
        # descriptor dicts], "holders": {(host, port, data_port)},
        # "envelope": (metadata, inband, buffers) | None,
        # "total_bytes": int}. The sharding descriptor lives HERE, next
        # to the location entry, so consumers can rebuild the array and
        # pull from any surviving holder after the owner dies.
        self.device_objects: Dict[str, dict] = {}
        # agent connections for remote nodes: node_id -> rpc.Connection
        self._node_agents: Dict[NodeID, object] = {}
        # Nodes whose agent health channel dropped, waiting out the
        # death-grace window (node_id -> grace task). A reconnecting
        # agent reattaches here instead of registering a fresh node.
        self._node_grace: Dict[NodeID, asyncio.Task] = {}
        # Unified retry envelope for head->agent pushes.
        self._rpc_retry = retry.RetryPolicy.from_config(config)
        self._object_waiters: Dict[str, List[asyncio.Future]] = {}
        # worker connection -> WorkerHandle
        self._conn_to_worker: Dict[object, WorkerHandle] = {}
        # node_id -> deque of grants waiting for a worker to register
        self._waiting_grants: Dict[NodeID, deque] = {}
        # respawn backoff after startup crashes (node_id keyed)
        self._spawn_backoff_s: Dict[NodeID, float] = {}
        self._spawn_backoff_until: Dict[NodeID, float] = {}
        # actor_id -> in-flight creation task (to avoid double-create)
        self._creating_actors: Set[ActorID] = set()
        # task events ring buffer (state API backend)
        self.task_events: deque = deque(maxlen=config.task_events_max_buffer_size)
        self._pump_task: Optional[asyncio.Task] = None
        self._shutdown = False
        # Actors restored from storage, recreated once a node joins.
        self._recreate_on_node_join: List[ActorID] = []
        # Memory watchdog (reference: memory_monitor.h) + kill reasons
        # (worker_id hex -> human-readable cause, served to owners).
        self._mem_monitor = None
        self._death_reasons: Dict[str, str] = {}
        # Cluster health plane: bounded metrics time-series + SLO alert
        # rules, fed from every metrics push landing in the KV
        # (core/health.py). Best-effort by contract.
        from ray_tpu.core.health import ClusterHealthPlane

        self.health = ClusterHealthPlane(config,
                                         session_dir=session_dir)
        # Control-plane load observatory: pubsub fan-out / KV write
        # amplification accounting (util/rpc_stats.py); the per-handler
        # call accounting itself lives in the process-global
        # ServerStats that core/rpc.py records into.
        from ray_tpu.util.rpc_stats import AmplificationStats

        self.rpc_amp = AmplificationStats()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def attach(self, port: int):
        """Called once the RPC server is listening."""
        self.port = port
        self.pool = WorkerPool(self.host, port, self.session_dir)
        self.pool.spawn_remote = self._spawn_remote
        self.pool.kill_remote = self._kill_remote
        self.scheduler = ClusterScheduler(
            self.pool, spread_threshold=self.config.scheduler_spread_threshold
        )
        self._load_persisted()
        # Preregister the full dispatch dict into the process-global
        # accounting table: every handler shows in rpc_stats/hotrpc
        # from boot (zero counts included), and the parity test can
        # assert a newly added h_* cannot dodge instrumentation.
        from ray_tpu.util import rpc_stats

        rpc_stats.server_stats().register_methods(self.handlers())
        self._pump_task = asyncio.get_running_loop().create_task(
            self._periodic_pump()
        )

    # ------------------------------------------------------------------
    # persistence (reference: gcs_table_storage.h:242 over store_client)
    # ------------------------------------------------------------------

    def _persist_actor(self, info: ActorInfo):
        if self.storage is None:
            return
        spec = info.creation_spec
        # Only detached actors outlive their driver; everything else dies
        # with the job and would be garbage after a restart.
        if spec is None or not getattr(spec, "detached", False):
            return
        try:
            if info.state == "DEAD":
                self.storage.delete("actors", info.actor_id.hex())
            else:
                self.storage.put("actors", info.actor_id.hex(), info)
        except Exception:
            logger.exception("actor persistence failed")

    def _persist_pg(self, info: PlacementGroupInfo):
        if self.storage is None:
            return
        try:
            if info.state == "REMOVED":
                self.storage.delete("pgs", info.pg_id.hex())
            else:
                self.storage.put("pgs", info.pg_id.hex(), info)
        except Exception:
            logger.exception("pg persistence failed")

    def _persist_job(self, job_id: JobID, job: dict):
        if self.storage is None:
            return
        try:
            self.storage.put("jobs", job_id.hex(), {
                "counter": self._job_counter,
                "state": job.get("state"),
                "start_time": job.get("start_time"),
                "end_time": job.get("end_time"),
            })
        except Exception:
            logger.exception("job persistence failed")

    def _bump_spawn_backoff(self, node_id: NodeID):
        delay = min(self._spawn_backoff_s.get(node_id, 0.5) * 2, 30.0)
        self._spawn_backoff_s[node_id] = delay
        self._spawn_backoff_until[node_id] = time.monotonic() + delay

    def _persist_kv(self, ns: str, key, value, deleted: bool = False):
        if self.storage is None:
            return
        row_key = f"{ns}\x00{key!r}"
        try:
            if deleted:
                self.storage.delete("kv", row_key)
            else:
                self.storage.put("kv", row_key, (ns, key, value))
        except Exception:
            logger.exception("kv persistence failed")

    def _load_persisted(self):
        """Reload durable tables on head (re)start. Loaded actors lost
        their workers with the previous head; they re-enter RESTARTING
        and are recreated once a node joins (node_manager.cc:1122
        HandleNotifyGCSRestart analog — here workers are respawned rather
        than reattached, since they die with the head)."""
        if self.storage is None:
            return
        for _, (ns, key, value) in self.storage.items("kv"):
            self.kv.setdefault(ns, {})[key] = value
        self._recreate_on_node_join: List[ActorID] = []
        for _, info in self.storage.items("actors"):
            if info.state == "DEAD":
                continue
            info.state = "RESTARTING"
            info.address = None
            info.node_id = None
            self.actors[info.actor_id] = info
            if info.name:
                self.named_actors[(info.namespace, info.name)] = info.actor_id
            self._recreate_on_node_join.append(info.actor_id)
        for _, info in self.storage.items("pgs"):
            info.state = "PENDING"  # re-place once nodes register
            for b in info.bundles:
                b.node_id = None
            self.placement_groups[info.pg_id] = info
        for key, job in self.storage.items("jobs"):
            self._job_counter = max(self._job_counter,
                                    job.get("counter", 0))
            # Rehydrate finished-job history so list_jobs() shows jobs
            # that ran before the restart (reference: GCS job-table
            # reload). A job live at crash time died with the head.
            try:
                job_id = JobID.from_hex(key)
            except Exception:
                continue
            self.jobs[job_id] = {
                # A job still RUNNING at crash time died with the head —
                # reporting it FINISHED would label a crashed job as
                # having completed.
                "state": ("FINISHED" if job.get("state") == "FINISHED"
                          else "DEAD"),
                "start_time": job.get("start_time"),
                "end_time": job.get("end_time"),
            }
        if self.actors or self.placement_groups:
            logger.info(
                "restored %d actor(s), %d placement group(s) from %s",
                len(self.actors), len(self.placement_groups),
                getattr(self.storage, "path", "?"))

    def _spawn_remote(self, node_id: NodeID, worker_id: WorkerID) -> bool:
        """WorkerPool hook: spawn on a remote host via its node agent.
        Returns False ONLY for head-host nodes (pool forks locally) — a
        remote node whose agent is gone must never fall back to a local
        fork (the task would run on the wrong machine)."""
        info = self.nodes_info.get(node_id)
        if info is None or info.agent_address is None:
            return False
        agent = self._node_agents.get(node_id)

        async def go():
            try:
                if agent is None:
                    raise RuntimeError("node agent disconnected")
                # sent=False-only retries: a spawn frame that reached the
                # agent may already have forked; replaying it would leak
                # a second process for the same worker id.
                # timeout_per_attempt bounds a lost/unanswered frame (a
                # drop fault, a wedged agent) — without it the call
                # awaits a response forever and the policy never runs.
                await self._rpc_retry.execute(
                    lambda: agent.call("spawn_worker",
                                       {"worker_id": worker_id.hex()}),
                    idempotent=False,
                    timeout_per_attempt=30.0,
                    should_retry=lambda e: not getattr(
                        agent, "closed", False),
                    label="spawn_worker")
            except Exception:
                logger.warning("spawn_worker on node %s failed",
                               node_id.hex()[:12])
                handle = self.pool.workers.get(worker_id)
                if handle is not None and handle.state == "STARTING":
                    self.pool.mark_dead(worker_id)
                    self._bump_spawn_backoff(node_id)
                    self._pump()

        asyncio.ensure_future(go())
        return True

    def _kill_remote(self, node_id: NodeID, worker_id: WorkerID) -> None:
        agent = self._node_agents.get(node_id)
        if agent is not None:
            agent.notify_forget("kill_worker",
                                {"worker_id": worker_id.hex()})

    def _memory_monitor(self):
        """Lazy so tests can flip the threshold per-head via config."""
        if self._mem_monitor is None:
            from ray_tpu.core import memory_monitor as mm

            def candidates():
                # Actors restart for free only if restarts remain; a
                # max_restarts=0 actor holds irreplaceable state and must
                # be the last resort (worker_killing_policy_group_by_
                # owner.cc ranks the same way).
                actor_restartable = {}
                for info in self.actors.values():
                    if info.address is not None:
                        actor_restartable[info.address.worker_id_hex] = \
                            self._actor_can_restart(info)
                out = []
                for h in self.pool.workers.values():
                    if h.pid <= 0 or h.state in ("DEAD", "STARTING"):
                        continue  # agent-managed or not yet running work
                    hexid = h.worker_id.hex()
                    if h.state == "ACTOR":
                        retriable = actor_restartable.get(hexid, False)
                    elif h.state == "LEASED":
                        retriable = h.task_retriable
                    else:
                        retriable = True  # idle
                    out.append(mm.VictimCandidate(
                        worker_id_hex=hexid, pid=h.pid,
                        retriable=retriable,
                        is_actor=h.state == "ACTOR",
                        started_at=h.task_started_at or h.started_at,
                    ))
                return out

            def kill(victim, reason):
                worker_id = WorkerID.from_hex(victim.worker_id_hex)
                self.record_death_reason(victim.worker_id_hex, reason)
                handle = self.pool.workers.get(worker_id)
                self.pool.kill(worker_id)
                if handle is not None:
                    self._on_worker_dead(handle)

            self._mem_monitor = mm.MemoryMonitor(
                threshold=self.config.memory_usage_threshold,
                candidates=candidates, kill=kill)
        return self._mem_monitor

    def record_death_reason(self, worker_id_hex: str, reason: str):
        self._death_reasons[worker_id_hex] = reason
        while len(self._death_reasons) > 256:
            self._death_reasons.pop(next(iter(self._death_reasons)))

    async def h_worker_death_reason(self, conn, payload):
        return {"reason": self._death_reasons.get(payload["worker_id"])}

    async def h_report_oom_kill(self, conn, payload):
        """A node agent killed one of its workers under memory pressure;
        park the reason so the owner's terminal error can name it."""
        self.record_death_reason(payload["worker_id"], payload["reason"])
        return {"ok": True}

    async def _periodic_pump(self):
        from ray_tpu.core.log_monitor import LogTailer

        tailer = LogTailer(os.path.join(self.session_dir, "logs"))
        while not self._shutdown:
            try:
                reaped = self.pool.reap_exited_starting()
                for handle in reaped:
                    logger.warning("worker %s exited before registering",
                                   handle.worker_id.hex()[:12])
                    self._bump_spawn_backoff(handle.node_id)
                self._pump()
                if self.config.memory_monitor_enabled:
                    self._memory_monitor().maybe_kill()
                # Head-local workers' logs stream like any node's
                # (node agents tail their own hosts).
                entries = tailer.poll()
                if entries:
                    self._publish("worker_logs",
                                  {"node": "head", "entries": entries})
                self._report_node_metrics()
                # Alerts must keep resolving when pushes stop arriving
                # (a stalled cluster can't be the thing that freezes
                # its own alert lifecycle).
                self.health.tick()
            except Exception:
                logger.exception("scheduler pump failed")
            if os.environ.get("RAY_TPU_DEBUG_PUMP"):
                self._debug_dump()
            await asyncio.sleep(0.2)

    _last_node_metrics = 0.0

    def _report_node_metrics(self):
        """Node states as gauges, SUSPECT (death-grace window) occupancy
        included — the one signal that distinguishes a healing partition
        from a real node loss."""
        now = time.monotonic()
        if now - self._last_node_metrics < 1.0:
            return
        self._last_node_metrics = now
        from ray_tpu.util import telemetry

        counts = {"ALIVE": 0, "SUSPECT": 0, "DEAD": 0}
        for info in self.nodes_info.values():
            counts[info.state] = counts.get(info.state, 0) + 1
        for state, n in counts.items():
            telemetry.set_gauge("ray_tpu_gcs_nodes", n, {"state": state})
        from ray_tpu.core.object_ref import get_core_worker

        if get_core_worker() is None:
            # Standalone head (head_main): no CoreWorker to push
            # through — write this process's snapshot straight into the
            # local KV so head-side metrics (scheduler, gcs nodes)
            # still reach collect_metrics / the dashboard. Ephemeral:
            # deliberately not persisted to the sqlite store.
            try:
                from ray_tpu.util import metrics as um

                snap = um.local_snapshot()
                if snap:
                    blob = json.dumps(
                        dict(snap, _meta=um.push_meta())).encode()
                    self.kv.setdefault("metrics", {})[b"metrics:head"] = blob
                    # Direct KV write bypasses h_kv_put; feed the
                    # health plane explicitly.
                    self.health.on_metrics_push(b"metrics:head", blob)
            except Exception as e:
                _swallow("gcs.metrics_snapshot", e)

    _last_debug_dump = 0.0

    def _debug_dump(self):
        now = time.monotonic()
        if now - self._last_debug_dump < 5.0:
            return
        self._last_debug_dump = now
        sch = self.scheduler
        states = {}
        for h in self.pool.workers.values():
            states[h.state] = states.get(h.state, 0) + 1
        print(
            f"[pump] pending={len(sch.pending)} "
            f"active_leases={len(sch.active_leases)} "
            f"avail={sch.available_resources()} "
            f"workers={states} "
            f"waiting_grants={ {k.hex()[:6]: len(v) for k, v in self._waiting_grants.items()} }",
            flush=True,
        )

    def add_node(self, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 agent_address: Optional[tuple] = None,
                 agent_conn=None) -> NodeID:
        node_id = NodeID.from_random()
        node = Node(node_id, ResourceSet(resources), labels)
        self.scheduler.add_node(node)
        self.nodes_info[node_id] = NodeInfo(
            node_id=node_id,
            address=agent_address[0] if agent_address else self.host,
            resources=dict(resources), labels=labels or {},
            agent_address=tuple(agent_address) if agent_address else None,
        )
        if not hasattr(self, "default_node_id"):
            self.default_node_id = node_id
        if agent_conn is not None:
            self._node_agents[node_id] = agent_conn
        from ray_tpu.util import flight_recorder

        flight_recorder.record("gcs", "node_alive",
                               node=node_id.hex()[:12],
                               resources=str(dict(resources)),
                               remote=agent_conn is not None)
        self._publish("node_state", {
            "node_id": node_id.hex(), "state": "ALIVE",
            "resources": dict(resources),
        })
        if self._recreate_on_node_join:
            restored, self._recreate_on_node_join = (
                self._recreate_on_node_join, [])
            for actor_id in restored:
                asyncio.get_running_loop().create_task(
                    self._create_actor(actor_id))
        self._pump()
        return node_id

    def remove_node(self, node_id: NodeID):
        from ray_tpu.util import flight_recorder

        flight_recorder.record("gcs", "node_dead", severity="error",
                               node=node_id.hex()[:12])
        self.scheduler.remove_node(node_id)
        info = self.nodes_info.get(node_id)
        if info:
            info.state = "DEAD"
        self._node_agents.pop(node_id, None)
        # Re-route grants parked waiting for a worker on this node: hand
        # their reserved resources back and resubmit to the scheduler (a
        # request only this node could ever satisfy then fails as
        # infeasible through the normal path).
        for lease, lease_id in self._waiting_grants.pop(node_id, ()):
            self.scheduler.release_lease(lease_id)
            if not lease.future.done():
                self.scheduler.submit(lease)
        # Kill that node's workers; their deaths cascade to actors/leases.
        for handle in list(self.pool.workers.values()):
            if handle.node_id == node_id:
                self.pool.kill(handle.worker_id)
                self._on_worker_dead(handle)
        # Every object copy on the node is gone with its store.
        for hex_id, nodes in list(self.object_locations.items()):
            nodes.discard(node_id)
            if not nodes:
                self.object_locations.pop(hex_id, None)
                # Keep sealed_objects: a head-host copy may still exist in
                # self.shm only for head nodes; if no locations remain the
                # object is lost and get() surfaces ObjectLostError.
                if not self.shm.contains(ObjectID.from_hex(hex_id)):
                    self.sealed_objects.pop(hex_id, None)
        self._publish("node_state", {"node_id": node_id.hex(), "state": "DEAD"})

    def handlers(self) -> dict:
        return {
            "register_worker": self.h_register_worker,
            "register_driver": self.h_register_driver,
            "register_node": self.h_register_node,
            "worker_exited_early": self.h_worker_exited_early,
            "locate_object": self.h_locate_object,
            "object_location_added": self.h_object_location_added,
            "object_lost": self.h_object_lost,
            "request_lease": self.h_request_lease,
            "return_worker": self.h_return_worker,
            "register_actor": self.h_register_actor,
            "get_actor_info": self.h_get_actor_info,
            "get_named_actor": self.h_get_named_actor,
            "list_named_actors": self.h_list_named_actors,
            "kill_actor": self.h_kill_actor,
            "actor_exited": self.h_actor_exited,
            "kv_put": self.h_kv_put,
            "kv_get": self.h_kv_get,
            "kv_del": self.h_kv_del,
            "kv_exists": self.h_kv_exists,
            "kv_keys": self.h_kv_keys,
            "subscribe": self.h_subscribe,
            "publish": self.h_publish,
            "object_sealed": self.h_object_sealed,
            "wait_object": self.h_wait_object,
            "free_objects": self.h_free_objects,
            "device_object_put": self.h_device_object_put,
            "locate_device_object": self.h_locate_device_object,
            "device_location_added": self.h_device_location_added,
            "device_location_removed": self.h_device_location_removed,
            "pin_object": self.h_pin_object,
            "unpin_object": self.h_unpin_object,
            "create_pg": self.h_create_pg,
            "remove_pg": self.h_remove_pg,
            "pg_ready": self.h_pg_ready,
            "get_pg": self.h_get_pg,
            "list_pgs": self.h_list_pgs,
            "get_nodes": self.h_get_nodes,
            "cluster_resources": self.h_cluster_resources,
            "available_resources": self.h_available_resources,
            "report_task_events": self.h_report_task_events,
            "list_task_events": self.h_list_task_events,
            "list_workers": self.h_list_workers,
            "list_actors": self.h_list_actors,
            "list_objects": self.h_list_objects,
            "list_jobs": self.h_list_jobs,
            "get_load": self.h_get_load,
            "worker_death_reason": self.h_worker_death_reason,
            "report_oom_kill": self.h_report_oom_kill,
            "ping": self.h_ping,
            "autoscaler_status": self.h_autoscaler_status,
            "metrics_history": self.h_metrics_history,
            "metrics_history_snapshot": self.h_metrics_history_snapshot,
            "alerts": self.h_alerts,
            "alerts_put_rule": self.h_alerts_put_rule,
            "rpc_stats": self.h_rpc_stats,
            "debug_dump_cluster": self.h_debug_dump_cluster,
            "debug_sched_state": self.h_debug_sched_state,
            "profile_capture_cluster": self.h_profile_capture_cluster,
            "device_trace_capture_cluster":
                self.h_device_trace_capture_cluster,
            # Serve the head-host node store for cross-node pulls.
            **object_transfer.serve_handlers(),
        }

    # ------------------------------------------------------------------
    # workers / drivers
    # ------------------------------------------------------------------

    async def h_register_worker(self, conn, payload):
        worker_id = WorkerID.from_hex(payload["worker_id"])
        address = (payload["host"], payload["port"])
        handle = self.pool.on_registered(worker_id, address, conn)
        if handle is None:
            return {"ok": False, "error": "unknown worker"}
        _stamp_caller(conn, "worker")
        self._conn_to_worker[conn] = handle
        self._spawn_backoff_s.pop(handle.node_id, None)
        self._spawn_backoff_until.pop(handle.node_id, None)
        prev_close = conn.on_close
        def on_close(c, _prev=prev_close):
            if _prev:
                _prev(c)
            h = self._conn_to_worker.pop(c, None)
            if h is not None:
                self._on_worker_dead(h)
        conn.on_close = on_close
        # A grant may be waiting for this worker's node.
        self._match_waiting_grants(handle.node_id)
        self._pump()
        return {"ok": True, "node_id": handle.node_id.hex()}

    async def h_register_node(self, conn, payload):
        """A node agent (remote host) joins the cluster. Its connection
        doubles as the health channel: close ⇒ grace window ⇒ node death
        (reference: node_manager.cc heartbeats / gcs_node_manager death
        handling). A payload carrying a known ``node_id`` is a reconnect
        from a briefly partitioned agent: reattach instead of
        registering a fresh node."""
        _stamp_caller(conn, "agent")
        prev_hex = payload.get("node_id")
        if prev_hex:
            node_id = NodeID.from_hex(prev_hex)
            if self._reattach_node(node_id, conn, payload):
                self._hook_agent_close(conn, node_id)
                return {"ok": True, "node_id": node_id.hex()}
            # Grace expired (node already removed) — fall through and
            # register as a brand-new node.
        node_id = self.add_node(
            payload["resources"], payload.get("labels"),
            agent_address=(payload["host"], payload["port"]),
            agent_conn=conn,
        )
        self._hook_agent_close(conn, node_id)
        return {"ok": True, "node_id": node_id.hex()}

    def _hook_agent_close(self, conn, node_id: NodeID):
        prev_close = conn.on_close

        def on_close(c, _prev=prev_close, _nid=node_id):
            if _prev:
                _prev(c)
            self._on_agent_conn_lost(_nid, c)

        conn.on_close = on_close

    def _reattach_node(self, node_id: NodeID, conn, payload) -> bool:
        """Reattach a reconnecting agent to its SUSPECT (or still-ALIVE)
        node within the grace window. Returns False when the node is
        gone (grace expired -> remove_node already ran)."""
        info = self.nodes_info.get(node_id)
        if info is None or info.state == "DEAD":
            return False
        grace_task = self._node_grace.pop(node_id, None)
        if grace_task is not None:
            grace_task.cancel()
        info.state = "ALIVE"
        sched_node = self.scheduler.nodes.get(node_id)
        if sched_node is not None:
            sched_node.state = "ALIVE"  # placements resume
        info.agent_address = (payload["host"], payload["port"])
        self._node_agents[node_id] = conn
        from ray_tpu.util import flight_recorder

        flight_recorder.record("gcs", "node_reattached",
                               node=node_id.hex()[:12])
        logger.info("node agent %s reconnected within grace window",
                    node_id.hex()[:12])
        self._publish("node_state", {
            "node_id": node_id.hex(), "state": "ALIVE",
            "resources": dict(info.resources),
        })
        self._pump()
        return True

    def _on_agent_conn_lost(self, node_id: NodeID, conn=None):
        """Agent health channel dropped. Instead of instantly promoting
        conn-close to node death, hold the node SUSPECT for the
        configured grace window — the agent reconnects with backoff and
        reattaches; only a grace timeout declares the node dead
        (reference: gcs_health_check_manager's failure threshold before
        death, vs raw channel state)."""
        info = self.nodes_info.get(node_id)
        if info is None or info.state == "DEAD":
            return
        # Only the CURRENT agent connection's close counts: a stale
        # close racing in after a successful reattach must not restart
        # the grace clock on the healthy replacement channel.
        if conn is not None and self._node_agents.get(node_id) not in (
                None, conn):
            return
        self._node_agents.pop(node_id, None)
        grace = self.config.gcs_node_death_grace_s
        if grace <= 0 or self._shutdown:
            logger.warning("node agent %s disconnected; removing node",
                           node_id.hex()[:12])
            self.remove_node(node_id)
            return
        if node_id in self._node_grace:
            return
        logger.warning(
            "node agent %s disconnected; %.1fs grace before declaring "
            "the node dead", node_id.hex()[:12], grace)
        from ray_tpu.util import flight_recorder

        flight_recorder.record("gcs", "node_suspect", severity="warn",
                               node=node_id.hex()[:12], grace_s=grace)
        info.state = "SUSPECT"
        # Mirror into the scheduler's node table: new leases must not
        # land on a node whose agent can't fork workers right now (the
        # spawn would fail and churn mark-dead/backoff for the whole
        # window); existing workers/leases keep running untouched.
        sched_node = self.scheduler.nodes.get(node_id)
        if sched_node is not None:
            sched_node.state = "SUSPECT"
        self._publish("node_state", {
            "node_id": node_id.hex(), "state": "SUSPECT",
        })
        self._node_grace[node_id] = asyncio.get_running_loop().create_task(
            self._grace_then_remove(node_id, grace))

    async def _grace_then_remove(self, node_id: NodeID, grace: float):
        try:
            await asyncio.sleep(grace)
        except asyncio.CancelledError:
            return  # agent reattached
        self._node_grace.pop(node_id, None)
        info = self.nodes_info.get(node_id)
        if info is None or info.state != "SUSPECT":
            return
        logger.warning("node %s grace window expired; declaring dead",
                       node_id.hex()[:12])
        self.remove_node(node_id)

    async def h_worker_exited_early(self, conn, payload):
        """Agent-reported death of a spawned worker that never registered
        (the remote analog of reap_exited_starting)."""
        worker_id = WorkerID.from_hex(payload["worker_id"])
        handle = self.pool.workers.get(worker_id)
        if handle is not None and handle.state == "STARTING":
            self.pool.mark_dead(worker_id)
            self._bump_spawn_backoff(handle.node_id)
            self._pump()
        return {"ok": True}

    async def h_object_lost(self, conn, payload):
        """Owner-reported loss of every reachable copy (before lineage
        recovery): forget the seal so wait_object blocks until the
        re-seal, and tell any still-listed remote holder to drop its
        copy — a transiently unreachable holder may hold a pinned
        primary that would otherwise leak until node death."""
        hex_id = payload["object_id"]
        self.sealed_objects.pop(hex_id, None)
        self.device_objects.pop(hex_id, None)
        self.shm.delete(ObjectID.from_hex(hex_id))
        for node_id in self.object_locations.pop(hex_id, set()):
            agent = self._node_agents.get(node_id)
            if agent is not None:
                try:
                    await agent.notify("free_objects",
                                       {"object_ids": [hex_id]})
                except Exception as e:
                    _swallow("gcs.lost_object_free", e,
                             object=hex_id[:16])
        return {"ok": True}

    async def h_object_location_added(self, conn, payload):
        """A node pulled a copy of a sealed object into its local store."""
        hex_id = payload["object_id"]
        if hex_id in self.sealed_objects:
            self.object_locations.setdefault(hex_id, set()).add(
                NodeID.from_hex(payload["node_id"]))
        return {"ok": True}

    async def h_register_driver(self, conn, payload):
        _stamp_caller(conn, "driver")
        self._job_counter += 1
        job_id = JobID.from_int(self._job_counter)
        self.jobs[job_id] = {
            "address": (payload["host"], payload["port"]),
            "worker_id": payload["worker_id"],
            "state": "RUNNING",
            "start_time": time.time(),
        }
        self._persist_job(job_id, self.jobs[job_id])
        if conn is not None and hasattr(conn, "on_close"):
            prev_close = conn.on_close
            def on_close(c, _prev=prev_close, _job=job_id):
                if _prev:
                    _prev(c)
                self._on_driver_exit(_job)
            conn.on_close = on_close
        return {
            "job_id": job_id.hex(),
            "session_dir": self.session_dir,
            # Same-host drivers can map the head's arena directly; remote
            # ones fail the shm attach and use the pull plane instead.
            "arena": os.environ.get("RAY_TPU_ARENA"),
            "default_node_id": (self.default_node_id.hex()
                                if hasattr(self, "default_node_id") else None),
            "nodes": [
                {"node_id": n.node_id.hex(), "resources": n.resources}
                for n in self.nodes_info.values()
            ],
        }

    def _on_driver_exit(self, job_id: JobID):
        job = self.jobs.get(job_id)
        if job:
            job["state"] = "FINISHED"
            job["end_time"] = time.time()
            self._persist_job(job_id, job)
        # Kill non-detached actors of the job.
        for actor_id, info in list(self.actors.items()):
            if info.job_id == job_id and info.state in ("ALIVE", "PENDING",
                                                        "RESTARTING"):
                spec = info.creation_spec
                if spec is not None and getattr(spec, "detached", False):
                    continue
                asyncio.get_running_loop().create_task(
                    self._kill_actor(actor_id, no_restart=True,
                                     reason="driver exited")
                )

    def _on_worker_dead(self, handle: WorkerHandle):
        logger.info("worker %s died (state=%s)", handle.worker_id.hex()[:12],
                    handle.state)
        from ray_tpu.util import flight_recorder

        flight_recorder.record(
            "gcs", "worker_dead", severity="warn",
            worker=handle.worker_id.hex()[:12],
            node=handle.node_id.hex()[:12], state=handle.state,
            reason=self._death_reasons.get(handle.worker_id.hex(), ""))
        self.pool.mark_dead(handle.worker_id)
        # Drop the dead process's telemetry snapshots: its last pushed
        # gauges (in-flight RPCs, router queue depth) would otherwise
        # read as live values forever — worst exactly during the chaos
        # soaks this plane instruments.
        wid = handle.worker_id.hex()
        self.kv.get("metrics", {}).pop(f"metrics:{wid}".encode(), None)
        self.kv.get("timeline", {}).pop(f"timeline:{wid}".encode(), None)
        # Drop the dead worker's pubsub subscriptions immediately (the
        # conn's own on_close also discards, but a kill-path death can
        # reach here while the socket still looks open).
        conn = handle.connection
        if conn is not None:
            for channel, subs in self.subscribers.items():
                if conn in subs:
                    subs.discard(conn)
                    self.rpc_amp.record_prune(channel, 1)
        # History keeps the dead proc's recorded points (that's the
        # point of history) but stops gauge carry-forward for it.
        self.health.on_proc_gone(f"metrics:{wid}")
        # The "flightring" namespace deliberately survives: a shipped
        # ring tail is exactly the evidence a SIGKILL'd worker left
        # behind, and debug_dump_cluster merges it for dead processes.
        # Retract the dead process's device-plane holder listings so
        # consumers don't burn a pull sweep on a vanished peer; the
        # manifest itself survives as long as any holder (or mirrored
        # envelope) does.
        if handle.address is not None:
            dead = tuple(handle.address)
            for entry in self.device_objects.values():
                entry["holders"] = {h for h in entry["holders"]
                                    if tuple(h[:2]) != dead}
        if handle.lease_id:
            self.scheduler.release_lease(handle.lease_id)
        # Actor death?
        for actor_id, info in list(self.actors.items()):
            if (
                info.address is not None
                and info.address.worker_id_hex == handle.worker_id.hex()
                and info.state in ("ALIVE", "RESTARTING")
            ):
                self._on_actor_worker_died(actor_id, info)
        self._pump()

    # ------------------------------------------------------------------
    # leases
    # ------------------------------------------------------------------

    async def h_request_lease(self, conn, payload):
        spec: TaskSpec = serialization.loads_control(payload["spec"])
        resources = ResourceSet(spec.resources)
        fut = asyncio.get_running_loop().create_future()
        lease = PendingLease(spec=spec, resources=resources, future=fut)
        self.scheduler.submit(lease)
        self._pump()
        try:
            worker, lease_id = await fut
        except ValueError as e:
            return {"granted": False, "infeasible": True, "error": str(e)}
        return {
            "granted": True,
            "lease_id": lease_id,
            "worker_id": worker.worker_id.hex(),
            "host": worker.address[0],
            "port": worker.address[1],
            "node_id": worker.node_id.hex(),
        }

    def _pump(self):
        if self.scheduler is None:
            return
        self._retry_pending_pgs()
        grants = self.scheduler.pump()
        for lease, node, pg_id, bundle_index, idle_worker in grants:
            lease_id = self.scheduler.next_lease_id()
            self.scheduler.record_lease(
                lease_id, node.node_id, lease.resources, pg_id, bundle_index
            )
            if idle_worker is not None:
                self._grant(lease, idle_worker, lease_id)
            else:
                self._waiting_grants.setdefault(node.node_id, deque()).append(
                    (lease, lease_id)
                )
        # Spawn workers to cover waiting grants, netting out spawns already
        # in flight — one lease request must not fork one process each time
        # the pump runs while an earlier spawn is still importing (a spawn
        # storm serializes every startup on small hosts and starves the very
        # grant it was meant to serve). Respawns after a startup crash back
        # off exponentially so a worker that dies during import doesn't turn
        # the 0.2s pump into a fork loop.
        now = time.monotonic()
        for node_id, queue in self._waiting_grants.items():
            if not queue:
                continue
            backoff_until = self._spawn_backoff_until.get(node_id, 0.0)
            if now < backoff_until:
                continue
            deficit = len(queue) - self.pool.starting_count(node_id)
            for _ in range(deficit):
                self.pool.spawn(node_id)

    def _grant(self, lease: PendingLease, worker: WorkerHandle, lease_id: str):
        if os.environ.get("RAY_TPU_DEBUG_LEASE"):
            print(f"[lease] grant {lease_id} w={worker.worker_id.hex()[:6]} "
                  f"prev_state={worker.state} fn={lease.spec.name or lease.spec.function_key[-12:]}",
                  flush=True)
        worker.state = "LEASED"
        worker.lease_id = lease_id
        worker.task_retriable = lease.spec.max_retries != 0
        worker.task_started_at = time.monotonic()
        if not lease.future.done():
            lease.future.set_result((worker, lease_id))
        else:
            # Requester gave up; return the worker and resources.
            self.scheduler.release_lease(lease_id)
            self.pool.push_idle(worker)

    def _match_waiting_grants(self, node_id: NodeID):
        queue = self._waiting_grants.get(node_id)
        while queue:
            worker = self.pool.pop_idle(node_id)
            if worker is None:
                return
            lease, lease_id = queue.popleft()
            self._grant(lease, worker, lease_id)

    async def h_return_worker(self, conn, payload):
        lease_id = payload["lease_id"]
        worker_id = WorkerID.from_hex(payload["worker_id"])
        self.scheduler.release_lease(lease_id)
        handle = self.pool.workers.get(worker_id)
        if os.environ.get("RAY_TPU_DEBUG_LEASE"):
            print(f"[lease] return {lease_id} w={worker_id.hex()[:6]} "
                  f"state={handle.state if handle else None} "
                  f"cur_lease={handle.lease_id if handle else None}",
                  flush=True)
        # Only idle the worker if this return matches its *current* lease;
        # a stale return (late idle-timer from a previous leaseholder) must
        # not free a worker that has since been re-leased to someone else.
        alive = (handle is not None and handle.connection is not None
                 and not getattr(handle.connection, "closed", False))
        if alive and handle.pid != -1:
            # The owner often notices a worker death (its push conn drops)
            # before the head's EOF is processed; poll the process so a
            # dead worker is never re-idled and re-granted.
            proc = self.pool._procs.get(worker_id)
            if proc is not None and proc.poll() is not None:
                alive = False
        if (handle and alive and handle.state == "LEASED"
                and handle.lease_id == lease_id):
            self.pool.push_idle(handle)
            self._match_waiting_grants(handle.node_id)
        self._pump()
        return {"ok": True}

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    async def h_register_actor(self, conn, payload):
        spec: TaskSpec = serialization.loads_control(payload["spec"])
        actor_id = spec.actor_id
        name_key = None
        if spec.actor_name:
            name_key = (spec.namespace, spec.actor_name)
            if name_key in self.named_actors:
                existing = self.named_actors[name_key]
                info = self.actors.get(existing)
                if info and info.state != "DEAD":
                    return {"ok": False,
                            "error": f"actor name {spec.actor_name!r} taken"}
        info = ActorInfo(
            actor_id=actor_id,
            job_id=spec.job_id,
            state="PENDING",
            name=spec.actor_name,
            namespace=spec.namespace,
            max_restarts=spec.max_restarts,
            creation_spec=spec,
        )
        self.actors[actor_id] = info
        if name_key:
            self.named_actors[name_key] = actor_id
        self._persist_actor(info)
        if getattr(spec, "detached", False):
            await self._commit_barrier()  # durable before the owner's ack
        asyncio.get_running_loop().create_task(self._create_actor(actor_id))
        return {"ok": True}

    async def _create_actor(self, actor_id: ActorID):
        """Lease a worker and push the creation task, retrying on worker
        failure while restarts remain (reference: gcs_actor_scheduler.h:
        111,259 and gcs_actor_manager.cc:684 idempotent restart). The
        retry loop lives HERE rather than in _on_actor_worker_died so a
        worker crash mid-creation (push raises ConnectionLost) cannot be
        lost to the _creating_actors re-entrancy guard."""
        if actor_id in self._creating_actors:
            return
        self._creating_actors.add(actor_id)
        try:
            while True:
                outcome = await self._create_actor_attempt(actor_id)
                if outcome != "retry":
                    return
                await asyncio.sleep(0.5)
        finally:
            self._creating_actors.discard(actor_id)

    async def _create_actor_attempt(self, actor_id: ActorID) -> str:
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return "done"
        spec = info.creation_spec
        fut = asyncio.get_running_loop().create_future()
        lease = PendingLease(
            spec=spec, resources=ResourceSet(spec.resources), future=fut,
            is_actor_creation=True,
        )
        self.scheduler.submit(lease)
        self._pump()
        try:
            worker, lease_id = await fut
        except ValueError as e:
            self._mark_actor_dead(actor_id, f"unschedulable: {e}")
            return "done"
        if info.state == "DEAD":  # killed while the lease was pending
            self.scheduler.release_lease(lease_id)
            self.pool.push_idle(worker)
            return "done"
        worker.state = "ACTOR"
        from ray_tpu.core.task_spec import Address

        info.address = Address(
            host=worker.address[0], port=worker.address[1],
            worker_id_hex=worker.worker_id.hex(),
        )
        info.node_id = worker.node_id
        try:
            result = await worker.connection.call(
                "create_actor",
                {"spec": serialization.dumps_control(spec)},
                timeout=None,
            )
        except Exception as e:
            # The worker died under the creation push (startup crash, OOM,
            # node loss). That is a restartable fault, not a user error.
            if info.state == "DEAD":
                # _on_actor_worker_died already spent the last restart
                # credit and resolved the actor.
                return "done"
            if info.address is None and info.state == "RESTARTING":
                # _on_actor_worker_died beat us to this fault (it clears
                # the address): the restart credit is already charged —
                # charging again here would burn two credits per fault.
                logger.warning(
                    "actor %s creation push failed (%s); retrying "
                    "(restart %d)", actor_id.hex()[:12], e,
                    info.num_restarts)
                return "retry"
            if self._actor_can_restart(info):
                info.num_restarts += 1
                info.state = "RESTARTING"
                info.address = None
                self._publish_actor(info)
                logger.warning(
                    "actor %s creation push failed (%s); retrying "
                    "(restart %d)", actor_id.hex()[:12], e,
                    info.num_restarts)
                return "retry"
            self._mark_actor_dead(actor_id, f"creation push failed: {e}")
            return "done"
        if not result.get("ok"):
            # Creation raised in __init__ — actor is dead; the error
            # object was already delivered to the owner.
            self._mark_actor_dead(actor_id,
                                  result.get("error", "creation failed"))
            return "done"
        if info.state != "DEAD":
            info.state = "ALIVE"
            self._persist_actor(info)
            self._publish_actor(info)
        return "done"

    @staticmethod
    def _actor_can_restart(info: ActorInfo) -> bool:
        return (info.max_restarts == -1
                or info.num_restarts < info.max_restarts)

    def _on_actor_worker_died(self, actor_id: ActorID, info: ActorInfo):
        if info.num_restarts < info.max_restarts or info.max_restarts == -1:
            info.num_restarts += 1
            info.state = "RESTARTING"
            info.address = None
            self._publish_actor(info)
            asyncio.get_running_loop().create_task(self._create_actor(actor_id))
        else:
            self._mark_actor_dead(actor_id, "worker died")

    def _mark_actor_dead(self, actor_id: ActorID, reason: str):
        info = self.actors.get(actor_id)
        if info is None:
            return
        info.state = "DEAD"
        info.death_cause = reason
        info.address = None
        self._persist_actor(info)
        self._publish_actor(info)

    def _publish_actor(self, info: ActorInfo):
        from ray_tpu.util import flight_recorder

        flight_recorder.record(
            "gcs", "actor_state",
            severity="error" if info.state == "DEAD" else "info",
            actor=info.actor_id.hex()[:16], state=info.state,
            restarts=info.num_restarts, cause=info.death_cause or "")
        self._publish("actor_state", {
            "actor_id": info.actor_id.hex(),
            "state": info.state,
            "address": (
                [info.address.host, info.address.port,
                 info.address.worker_id_hex]
                if info.address else None
            ),
            "num_restarts": info.num_restarts,
            "death_cause": info.death_cause,
        })

    def _actor_info_payload(self, info: ActorInfo) -> dict:
        return {
            "actor_id": info.actor_id.hex(),
            "state": info.state,
            "name": info.name,
            "namespace": info.namespace,
            "address": (
                [info.address.host, info.address.port,
                 info.address.worker_id_hex]
                if info.address else None
            ),
            "num_restarts": info.num_restarts,
            "max_restarts": info.max_restarts,
            "death_cause": info.death_cause,
            "job_id": info.job_id.hex(),
            "node_id": info.node_id.hex() if info.node_id else None,
        }

    async def h_get_actor_info(self, conn, payload):
        actor_id = ActorID.from_hex(payload["actor_id"])
        info = self.actors.get(actor_id)
        if info is None:
            return {"found": False}
        return {"found": True, **self._actor_info_payload(info)}

    async def h_get_named_actor(self, conn, payload):
        key = (payload.get("namespace", ""), payload["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return {"found": False}
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return {"found": False}
        return {"found": True, **self._actor_info_payload(info)}

    async def h_list_named_actors(self, conn, payload):
        all_ns = payload.get("all_namespaces", False)
        namespace = payload.get("namespace", "")
        out = []
        for (ns, name), actor_id in self.named_actors.items():
            info = self.actors.get(actor_id)
            if info is None or info.state == "DEAD":
                continue
            if all_ns or ns == namespace:
                out.append({"namespace": ns, "name": name})
        return out

    async def h_kill_actor(self, conn, payload):
        actor_id = ActorID.from_hex(payload["actor_id"])
        await self._kill_actor(actor_id, payload.get("no_restart", True),
                               reason="ray_tpu.kill")
        return {"ok": True}

    async def _kill_actor(self, actor_id: ActorID, no_restart: bool,
                          reason: str):
        info = self.actors.get(actor_id)
        if info is None or info.state == "DEAD":
            return
        if no_restart:
            info.max_restarts = info.num_restarts  # block further restarts
        address = info.address
        if address is not None:
            worker_id = WorkerID.from_hex(address.worker_id_hex)
            handle = self.pool.workers.get(worker_id)
            if handle and handle.connection and not handle.connection.closed:
                try:
                    await handle.connection.notify("exit_worker", {})
                except Exception as e:
                    # The hard kill below still lands; record the soft
                    # path's failure.
                    _swallow("gcs.kill_actor_exit_notify", e,
                             worker=worker_id.hex()[:16])
            # Ensure the process dies even if it ignores the notify.
            await asyncio.sleep(0)
            if handle:
                self.pool.kill(worker_id)
                self._on_worker_dead(handle)
        if no_restart:
            self._mark_actor_dead(actor_id, reason)

    async def h_actor_exited(self, conn, payload):
        """Graceful exit (__ray_terminate__ equivalent)."""
        actor_id = ActorID.from_hex(payload["actor_id"])
        info = self.actors.get(actor_id)
        if info:
            info.max_restarts = info.num_restarts
            self._mark_actor_dead(actor_id, "exited gracefully")
        return {"ok": True}

    # ------------------------------------------------------------------
    # KV
    # ------------------------------------------------------------------

    async def _commit_barrier(self):
        """Block the reply (not the loop) until every enqueued storage
        mutation is committed. Durable writes must be durable before the
        client sees the ack (reference: GCS acks after the redis write) —
        otherwise kv_put → head SIGKILL loses an acknowledged write."""
        if self.storage is None:
            return
        await asyncio.get_running_loop().run_in_executor(
            None, self.storage.flush)

    #: KV namespaces holding live telemetry: never persisted — every
    #: process re-pushes within seconds, a restarted head must not
    #: resurrect dead workers' gauges, and the 2s push cadence must not
    #: pay the sqlite fsync path.
    EPHEMERAL_KV_NS = ("metrics", "timeline", "flightring")

    async def h_kv_put(self, conn, payload):
        ns_name = payload.get("ns", "")
        ns = self.kv.setdefault(ns_name, {})
        key = payload["key"]
        if not payload.get("overwrite", True) and key in ns:
            return {"added": False}
        value = payload["value"]
        ns[key] = value
        fanout = 0
        if ns_name == "metrics":
            # Health plane rides the push: append into the history
            # store + sweep the alert rules (never raises). That ingest
            # is one downstream delivery beyond the store write.
            self.health.on_metrics_push(key, value)
            fanout += 1
        fanout += len(self.subscribers.get(f"kv:{ns_name}", ()))
        try:
            nbytes = len(value)
        except TypeError:
            nbytes = 0
        self.rpc_amp.record_kv_put(ns_name, nbytes, fanout)
        if ns_name not in self.EPHEMERAL_KV_NS:
            self._persist_kv(ns_name, key, value)
            await self._commit_barrier()
        return {"added": True}

    async def h_kv_get(self, conn, payload):
        ns = self.kv.get(payload.get("ns", ""), {})
        return {"value": ns.get(payload["key"])}

    async def h_kv_del(self, conn, payload):
        ns_name = payload.get("ns", "")
        ns = self.kv.get(ns_name, {})
        existed = ns.pop(payload["key"], None) is not None
        if existed and ns_name not in self.EPHEMERAL_KV_NS:
            self._persist_kv(ns_name, payload["key"], None,
                             deleted=True)
            await self._commit_barrier()
        return {"deleted": existed}

    async def h_kv_exists(self, conn, payload):
        ns = self.kv.get(payload.get("ns", ""), {})
        return {"exists": payload["key"] in ns}

    async def h_kv_keys(self, conn, payload):
        ns = self.kv.get(payload.get("ns", ""), {})
        prefix = payload.get("prefix", b"")
        return {"keys": [k for k in ns if k.startswith(prefix)]}

    # ------------------------------------------------------------------
    # pubsub
    # ------------------------------------------------------------------

    async def h_subscribe(self, conn, payload):
        channel = payload["channel"]
        self.subscribers.setdefault(channel, set()).add(conn)
        prev_close = getattr(conn, "on_close", None)
        def on_close(c, _prev=prev_close):
            if _prev:
                _prev(c)
            for subs in self.subscribers.values():
                subs.discard(c)
        if hasattr(conn, "on_close"):
            conn.on_close = on_close
        return {"ok": True}

    async def h_publish(self, conn, payload):
        self._publish(payload["channel"], payload["data"])
        return {"ok": True}

    def _publish(self, channel: str, data):
        subs = self.subscribers.get(channel)
        if not subs:
            return
        # Prune dead subscriber conns BEFORE fanning out: without this
        # every publish keeps notifying dead peers forever (swallowing
        # the error each time), so fan-out cost grows monotonically
        # with worker churn.
        dead = [p for p in subs if getattr(p, "closed", False)]
        for p in dead:
            subs.discard(p)
        if dead:
            from ray_tpu.util import flight_recorder

            flight_recorder.record(
                "gcs", "subscriber_pruned", channel=channel,
                pruned=len(dead))
        for peer in list(subs):
            try:
                peer.notify_forget("pubsub",
                                   {"channel": channel, "data": data})
            except Exception as e:
                _swallow("gcs.pubsub_publish", e, channel=channel)
        self.rpc_amp.record_publish(channel, len(subs),
                                    _payload_nbytes(data),
                                    pruned=len(dead))

    # ------------------------------------------------------------------
    # object directory
    # ------------------------------------------------------------------

    async def h_object_sealed(self, conn, payload):
        hex_id = payload["object_id"]
        size = payload["size"]
        self.sealed_objects[hex_id] = size
        node_id = self._sealing_node(conn, payload)
        self.object_locations.setdefault(hex_id, set()).add(node_id)
        if self._node_agents.get(node_id) is None:
            # Head-host store: account the seal in the head's shm book.
            self.shm.mark_sealed(ObjectID.from_hex(hex_id), size)
        for fut in self._object_waiters.pop(hex_id, []):
            if not fut.done():
                fut.set_result(True)
        return {"ok": True}

    def _sealing_node(self, conn, payload) -> NodeID:
        node_hex = payload.get("node_id")
        if node_hex:
            return NodeID.from_hex(node_hex)
        handle = self._conn_to_worker.get(conn)
        if handle is not None:
            return handle.node_id
        return self.default_node_id

    async def h_locate_object(self, conn, payload):
        """Object-directory lookup: which nodes hold a sealed copy, and
        where to pull it from (fetch-server addresses)."""
        hex_id = payload["object_id"]
        if hex_id not in self.sealed_objects:
            return {"found": False}
        locations = []
        for node_id in self.object_locations.get(hex_id, set()):
            info = self.nodes_info.get(node_id)
            # SUSPECT (in-grace) nodes stay listed: only the head-side
            # health channel blipped; the pull plane may still reach
            # them, and the puller's retry sweep tolerates the ones it
            # can't.
            if info is None or info.state == "DEAD":
                continue
            if info.agent_address is not None:
                locations.append(list(info.agent_address))
            else:
                locations.append([self.host, self.port])
        return {"found": True, "size": self.sealed_objects[hex_id],
                "locations": locations,
                "nodes": [n.hex() for n in
                          self.object_locations.get(hex_id, set())]}

    async def h_wait_object(self, conn, payload):
        hex_id = payload["object_id"]
        if hex_id in self.sealed_objects:
            return {"sealed": True}
        fut = asyncio.get_running_loop().create_future()
        self._object_waiters.setdefault(hex_id, []).append(fut)
        timeout = payload.get("timeout")
        try:
            await asyncio.wait_for(fut, timeout)
            return {"sealed": True}
        except asyncio.TimeoutError:
            return {"sealed": False}

    async def h_free_objects(self, conn, payload):
        remote_by_agent: Dict[object, List[str]] = {}
        for hex_id in payload["object_ids"]:
            self.sealed_objects.pop(hex_id, None)
            self.device_objects.pop(hex_id, None)
            self.shm.delete(ObjectID.from_hex(hex_id))
            for node_id in self.object_locations.pop(hex_id, set()):
                agent = self._node_agents.get(node_id)
                if agent is not None:
                    remote_by_agent.setdefault(agent, []).append(hex_id)
        for agent, hex_ids in remote_by_agent.items():
            try:
                await agent.notify("free_objects", {"object_ids": hex_ids})
            except Exception:  # lint: allow-silent(agent death cleans its whole store anyway)
                pass
        return {"ok": True}

    # ---- device-native object plane (core/device_objects.py) ----

    async def h_device_object_put(self, conn, payload):
        """Owner registered a device-plane object: record the sharding
        manifest + envelope next to the location entry, with the owner
        as the first holder."""
        hex_id = payload["object_id"]
        holder = tuple(payload["holder"])
        envelope = payload.get("envelope")
        self.device_objects[hex_id] = {
            "manifest": payload.get("manifest") or [],
            "holders": {holder},
            "envelope": (tuple(envelope) if envelope is not None
                         else None),
            "total_bytes": int(payload.get("total_bytes") or 0),
        }
        return {"ok": True}

    async def h_locate_device_object(self, conn, payload):
        entry = self.device_objects.get(payload["object_id"])
        if entry is None:
            return {"found": False}
        envelope = entry["envelope"]
        return {
            "found": True,
            "holders": [list(h) for h in entry["holders"]],
            "manifest": entry["manifest"],
            "total_bytes": entry["total_bytes"],
            "envelope": (list(envelope) if envelope is not None
                         else None),
        }

    async def h_device_location_added(self, conn, payload):
        entry = self.device_objects.get(payload["object_id"])
        if entry is not None:
            entry["holders"].add(tuple(payload["holder"]))
        return {"ok": True}

    async def h_device_location_removed(self, conn, payload):
        entry = self.device_objects.get(payload["object_id"])
        if entry is not None:
            entry["holders"].discard(tuple(payload["holder"]))
        return {"ok": True}

    async def h_pin_object(self, conn, payload):
        self.shm.pin(ObjectID.from_hex(payload["object_id"]))
        return {"ok": True}

    async def h_unpin_object(self, conn, payload):
        self.shm.unpin(ObjectID.from_hex(payload["object_id"]))
        return {"ok": True}

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------

    async def h_create_pg(self, conn, payload):
        pg_id = PlacementGroupID.from_random()
        bundles = [ResourceSet(b) for b in payload["bundles"]]
        strategy = payload.get("strategy", "PACK")
        info = PlacementGroupInfo(
            pg_id=pg_id,
            bundles=[Bundle(resources=b) for b in payload["bundles"]],
            strategy=strategy,
            name=payload.get("name", ""),
        )
        self.placement_groups[pg_id] = info
        if self.scheduler.try_place_bundles(pg_id, bundles, strategy):
            info.state = "CREATED"
            states = self.scheduler.pg_bundles[pg_id]
            for bundle, st in zip(info.bundles, states):
                bundle.node_id = st.node_id
            for fut in self._pg_waiters.pop(pg_id, []):
                if not fut.done():
                    fut.set_result(True)
        # else: stays PENDING; _retry_pending_pgs retries on every pump.
        self._persist_pg(info)
        await self._commit_barrier()
        return {"pg_id": pg_id.hex(), "state": info.state}

    def _retry_pending_pgs(self):
        for pg_id, info in self.placement_groups.items():
            if info.state != "PENDING":
                continue
            bundles = [ResourceSet(b.resources) for b in info.bundles]
            if self.scheduler.try_place_bundles(pg_id, bundles, info.strategy):
                info.state = "CREATED"
                states = self.scheduler.pg_bundles[pg_id]
                for bundle, st in zip(info.bundles, states):
                    bundle.node_id = st.node_id
                for fut in self._pg_waiters.pop(pg_id, []):
                    if not fut.done():
                        fut.set_result(True)
                self._persist_pg(info)

    async def h_remove_pg(self, conn, payload):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        info = self.placement_groups.get(pg_id)
        if info:
            info.state = "REMOVED"
            self.scheduler.remove_pg(pg_id)
            self._persist_pg(info)
            self._pump()
        return {"ok": True}

    async def h_pg_ready(self, conn, payload):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        info = self.placement_groups.get(pg_id)
        if info is None:
            return {"ready": False, "error": "not found"}
        if info.state == "CREATED":
            return {"ready": True}
        fut = asyncio.get_running_loop().create_future()
        self._pg_waiters.setdefault(pg_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, payload.get("timeout"))
            return {"ready": True}
        except asyncio.TimeoutError:
            return {"ready": False}

    async def h_get_pg(self, conn, payload):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        info = self.placement_groups.get(pg_id)
        if info is None:
            return {"found": False}
        return {
            "found": True,
            "pg_id": pg_id.hex(),
            "state": info.state,
            "strategy": info.strategy,
            "bundles": [
                {"resources": b.resources,
                 "node_id": b.node_id.hex() if b.node_id else None}
                for b in info.bundles
            ],
        }

    async def h_list_pgs(self, conn, payload):
        return [
            {"pg_id": pg_id.hex(), "state": info.state, "name": info.name,
             "strategy": info.strategy}
            for pg_id, info in self.placement_groups.items()
        ]

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    async def h_get_nodes(self, conn, payload):
        return [
            {
                "node_id": info.node_id.hex(),
                "address": info.address,
                "resources": info.resources,
                "labels": info.labels,
                "state": info.state,
            }
            for info in self.nodes_info.values()
        ]

    async def h_cluster_resources(self, conn, payload):
        return self.scheduler.cluster_resources()

    async def h_available_resources(self, conn, payload):
        return self.scheduler.available_resources()

    async def h_report_task_events(self, conn, payload):
        for event in payload["events"]:
            self.task_events.append(event)
        return {"ok": True}

    async def h_list_task_events(self, conn, payload):
        limit = payload.get("limit", 1000)
        events = list(self.task_events)[-limit:]
        return {"events": events}

    async def h_list_workers(self, conn, payload):
        return [
            {
                "worker_id": h.worker_id.hex(),
                "node_id": h.node_id.hex(),
                "pid": h.pid,
                "state": h.state,
            }
            for h in self.pool.workers.values()
        ]

    async def h_list_actors(self, conn, payload):
        out = []
        for info in self.actors.values():
            row = self._actor_info_payload(info)
            row["class_name"] = (info.creation_spec.name.split(".")[0]
                                 if info.creation_spec else None)
            out.append(row)
        return {"actors": out}

    async def h_list_objects(self, conn, payload):
        from ray_tpu.core.object_store import _spill_path

        rows = []
        for oid, size in self.sealed_objects.items():
            locs = sorted(n.hex() for n in
                          self.object_locations.get(oid, set()))
            object_id = ObjectID.from_hex(oid)
            in_head = self.shm.contains(object_id)
            if locs or in_head:
                state = "SEALED"
            elif os.path.exists(_spill_path(object_id)):
                # Head-node store overflowed this one to disk.
                state = "SPILLED"
            else:
                state = "LOST"
            rows.append({"object_id": oid, "size_bytes": size,
                         "state": state, "locations": locs})
        return {"objects": rows}

    async def h_list_jobs(self, conn, payload):
        return {"jobs": [
            {"job_id": job_id.hex(), **{k: v for k, v in info.items()
                                        if k != "address"}}
            for job_id, info in self.jobs.items()
        ]}

    async def h_get_load(self, conn, payload):
        """Autoscaler input (reference: GcsAutoscalerStateManager /
        monitor.py update_load_metrics): pending demand shapes + per-node
        utilization."""
        pending = [lease.resources.to_dict()
                   for lease in self.scheduler.pending]
        leases_by_node: Dict[str, int] = {}
        for (node_id, _res, _pg, _bi) in self.scheduler.active_leases.values():
            leases_by_node[node_id.hex()] = \
                leases_by_node.get(node_id.hex(), 0) + 1
        nodes = []
        for info in self.nodes_info.values():
            node = self.scheduler.nodes.get(info.node_id)
            nodes.append({
                "node_id": info.node_id.hex(),
                "state": info.state,
                "total": dict(info.resources),
                "available": (node.resources.available.to_dict()
                              if node and info.state == "ALIVE" else {}),
                "active_leases": leases_by_node.get(info.node_id.hex(), 0),
                "labels": dict(info.labels),
            })
        return {"pending": pending, "nodes": nodes}

    async def h_ping(self, conn, payload):
        return {"ok": True, "time": time.time()}

    async def h_autoscaler_status(self, conn, payload):
        """Monitor introspection for CLI/dashboard (``ray status``
        analog). ``self.autoscaler`` is set by whoever runs a Monitor
        in this process (HeadNode with RAY_TPU_AUTOSCALER=1)."""
        monitor = getattr(self, "autoscaler", None)
        if monitor is None:
            return {"enabled": False}
        return {"enabled": True, **monitor.status()}

    # -- cluster health plane (core/health.py) -------------------------

    async def h_metrics_history(self, conn, payload):
        """Series index (no name) or windowed points / aggregates for
        one catalog metric (``name`` + optional ``window_s`` / ``agg``
        / ``tags`` / ``max_points``)."""
        return self.health.history_reply(payload or {})

    async def h_metrics_history_snapshot(self, conn, payload):
        """Full store dump for debug bundles and bench artifacts."""
        return self.health.snapshot_reply(payload or {})

    async def h_alerts(self, conn, payload):
        """Firing alerts, recent episodes (newest first), and the live
        rule set — swept on demand so the answer is current."""
        return self.health.alerts_reply()

    async def h_alerts_put_rule(self, conn, payload):
        """Add/replace one validated alert rule (or ``{"remove":
        name}``). Validation failures come back as ``{"ok": False}``,
        not exceptions — the CLI prints them."""
        return self.health.put_rule(payload or {})

    # -- control-plane load observatory (util/rpc_stats.py) ------------

    async def h_rpc_stats(self, conn, payload):
        """Head-process inbound-call accounting (per-handler times /
        bytes / callers), event-loop lag (head-local probes + the
        cluster-wide lag series from the history store), and pubsub/KV
        amplification factors. One payload feeds the hotrpc CLI,
        ``GET /rpc``, and the debug bundle ``rpc/`` section."""
        from ray_tpu.util import rpc_stats

        payload = payload or {}
        snap = rpc_stats.server_stats().snapshot(
            top=int(payload.get("top") or 20))
        snap["loops"] = rpc_stats.probe_summaries()
        snap["amplification"] = self.rpc_amp.snapshot()
        lag = []
        if self.health.enabled:
            window_s = float(payload.get("window_s") or 300.0)
            p99 = {tuple(sorted(r["tags"].items())): r["value"]
                   for r in self.health.store.window_agg(
                       "ray_tpu_event_loop_lag_seconds", "p99",
                       window_s)}
            for r in self.health.store.window_agg(
                    "ray_tpu_event_loop_lag_seconds", "p50", window_s):
                key = tuple(sorted(r["tags"].items()))
                lag.append({"tags": r["tags"], "p50_s": r["value"],
                            "p99_s": p99.get(key)})
        snap["loop_lag_cluster"] = lag
        return snap

    # ------------------------------------------------------------------
    # debug plane (reference: `ray stack` / state-API debug dumps)
    # ------------------------------------------------------------------

    async def h_debug_dump_cluster(self, conn, payload):
        """Fan the per-process ``debug_dump`` out to every reachable
        process — registered workers (over their head connections) and
        remote node agents — plus this head process itself. Unreachable
        peers come back as error entries instead of failing the dump:
        a debug plane that dies with the thing it debugs is useless."""
        payload = payload or {}
        req = {
            "include_events": payload.get("include_events", True),
            "include_stacks": payload.get("include_stacks", True),
            "event_limit": payload.get("event_limit"),
        }
        timeout = payload.get("timeout_s", 5.0)
        targets = []
        for h in self.pool.workers.values():
            c = h.connection
            if c is not None and not getattr(c, "closed", False):
                targets.append((f"worker:{h.worker_id.hex()}",
                                h.node_id.hex(), h.pid, c))
        for node_id, agent in self._node_agents.items():
            if not getattr(agent, "closed", False):
                targets.append((f"agent:{node_id.hex()}",
                                node_id.hex(), None, agent))

        async def one(source, node_hex, pid, c):
            try:
                rep = await c.call("debug_dump", req, timeout=timeout)
                rep["source"] = source
                rep.setdefault("node_id", node_hex)
                if pid is not None and pid > 0:
                    rep.setdefault("pid", pid)
                return rep
            except Exception as e:  # noqa: BLE001 — dump must survive
                return {"source": source, "node_id": node_hex,
                        "error": f"{type(e).__name__}: {e}"}

        entries = list(await asyncio.gather(
            *(one(*t) for t in targets)))
        from ray_tpu.util import flight_recorder

        head_entry = {
            "source": "head",
            "pid": os.getpid(),
            "node_id": (self.default_node_id.hex()
                        if hasattr(self, "default_node_id") else None),
            "ts": time.time(),
            "stacks": (flight_recorder.dump_stacks()
                       if req["include_stacks"] else {}),
        }
        if req["include_events"]:
            head_entry["events"] = flight_recorder.snapshot(
                limit=req["event_limit"])
        entries = [head_entry] + entries
        if req["include_events"]:
            entries.extend(self._shipped_ring_entries(entries))
        return {"entries": entries, "ts": time.time()}

    #: Shipped-ring retention: enough to cover any realistic postmortem
    #: window without letting worker churn grow the head (one ~256-event
    #: blob per error-recording worker) or bury fresh evidence in a dump
    #: under weeks of cleanly-exited processes' stale rings.
    FLIGHTRING_MAX_ENTRIES = 64
    FLIGHTRING_MAX_AGE_S = 6 * 3600.0

    def _prune_flightring(self) -> None:
        ns = self.kv.get("flightring")
        if not ns:
            return
        now = time.time()
        rows = []
        for key, blob in list(ns.items()):
            try:
                ts = float(json.loads(bytes(blob).decode())
                           .get("ts") or 0.0)
            except (ValueError, TypeError):
                ts = 0.0
            rows.append((key, ts))
        rows.sort(key=lambda kv: kv[1])
        drop = len(rows) - self.FLIGHTRING_MAX_ENTRIES
        for key, ts in rows:
            if drop > 0 or now - ts > self.FLIGHTRING_MAX_AGE_S:
                ns.pop(key, None)
                drop -= 1

    def _shipped_ring_entries(self, live_entries) -> list:
        """Shipped flight-recorder ring tails (KV ns "flightring") for
        processes the fan-out could NOT reach — a SIGKILL'd worker's
        last error-severity window survives here. Processes that
        answered live supersede their shipped (older) copy; stale
        entries age out (_prune_flightring) so churn can't bury the
        ring that matters."""
        self._prune_flightring()
        reached = {e.get("source") for e in live_entries
                   if not e.get("error")}
        # Live drivers ship rings too (same error-event trigger) but
        # are not fan-out targets — they splice themselves into dumps
        # client-side. Their shipped copy must not masquerade as a
        # dead worker's.
        live_driver_wids = {job.get("worker_id")
                            for job in self.jobs.values()
                            if job.get("state") == "RUNNING"}
        out = []
        for key, blob in list(self.kv.get("flightring", {}).items()):
            try:
                wid = bytes(key).decode().split(":", 1)[1]
            except (IndexError, UnicodeDecodeError):
                continue
            if f"worker:{wid}" in reached or wid in live_driver_wids:
                continue
            try:
                data = json.loads(bytes(blob).decode())
            except ValueError:
                continue
            out.append({
                "source": f"shipped:worker:{wid}",
                "worker_id": wid,
                "shipped": True,
                "pid": data.get("pid"),
                "node_id": data.get("node_id"),
                "ts": data.get("ts"),
                "events": data.get("events", []),
                "stacks": {},
            })
        return out

    def _fanout_targets(self, kind: str, ident: str):
        """Resolve a capture fan-out target set: ``(targets, error)``
        where targets are ``(source, node_hex, connection)`` rows.
        Shared by the host-sampler and device-trace fan-outs — the
        worker|task|actor|all grammar must stay identical between
        them. ``kind`` is pre-validated by the callers."""
        def live_workers(prefix=None):
            found = []
            for h in self.pool.workers.values():
                c = h.connection
                if c is None or getattr(c, "closed", False):
                    continue
                if prefix and not h.worker_id.hex().startswith(prefix):
                    continue
                found.append((f"worker:{h.worker_id.hex()}",
                              h.node_id.hex(), c))
            return found

        if kind == "worker":
            if not ident:
                return [], "worker id required"
            targets = live_workers(ident)
            if not targets:
                return [], f"no live worker with id prefix {ident!r}"
            return targets, None
        if kind == "actor":
            if not ident:
                return [], "actor id required"
            wid = None
            for actor_id, info in self.actors.items():
                if (actor_id.hex().startswith(ident)
                        and info.address is not None):
                    wid = info.address.worker_id_hex
                    break
            if wid is None:
                return [], f"no live actor with id prefix {ident!r}"
            targets = live_workers(wid)
            if not targets:
                return [], (f"actor {ident[:16]}'s worker {wid[:12]} "
                            "is not reachable")
            return targets, None
        if kind == "task":
            if not ident:
                return [], "task id required"
            wid = None
            state = None
            for ev in reversed(self.task_events):
                if (ev.get("task_id", "").startswith(ident)
                        and ev.get("worker_id")):
                    wid, state = ev["worker_id"], ev.get("state")
                    break
            if wid is None:
                return [], (f"no task event with id prefix {ident!r} "
                            "names a worker (wrong id, or events "
                            "rotated out)")
            targets = live_workers(wid)
            if not targets:
                return [], (f"task {ident[:16]}'s worker {wid[:12]} "
                            f"(last state {state}) is not reachable")
            return targets, None
        # all
        targets = live_workers()
        for node_id, agent in self._node_agents.items():
            if not getattr(agent, "closed", False):
                targets.append((f"agent:{node_id.hex()}",
                                node_id.hex(), agent))
        return targets, None

    async def _capture_fanout(self, kind: str, ident: str, method: str,
                              req: dict, timeout: float,
                              head_capture) -> dict:
        """Common fan-out body for the profile / device-trace capture
        handlers: resolve targets, call ``method`` on each with
        per-source error entries, and (for ``kind=all``) run
        ``head_capture`` in an executor for this head's own slice."""
        targets, error = self._fanout_targets(kind, ident)
        if error:
            return {"entries": [], "error": error}

        async def one(source, node_hex, c):
            try:
                rep = await c.call(method, req, timeout=timeout)
                rep["source"] = source
                rep.setdefault("node_id", node_hex)
                return rep
            except Exception as e:  # noqa: BLE001 — capture must survive peers
                return {"source": source, "node_id": node_hex,
                        "error": f"{type(e).__name__}: {e}"}

        gathered = asyncio.gather(*(one(*t) for t in targets))
        if kind == "all":
            head_cap, entries = await asyncio.gather(
                asyncio.get_running_loop().run_in_executor(
                    None, head_capture),
                gathered)
            head_cap["source"] = "head"
            head_cap["node_id"] = (self.default_node_id.hex()
                                   if hasattr(self, "default_node_id")
                                   else None)
            entries = [head_cap] + list(entries)
        else:
            entries = list(await gathered)
        return {"entries": entries, "ts": time.time(), **req}

    async def h_profile_capture_cluster(self, conn, payload):
        """Fan the ``profile_capture`` sampling window out — to one
        worker (``kind=worker``), the worker running a task
        (``kind=task``, resolved through the task-event store), an
        actor's worker (``kind=actor``), or every reachable process
        plus this head itself (``kind=all``). Unreachable peers come
        back as error entries, mirroring debug_dump_cluster."""
        payload = payload or {}
        kind = payload.get("kind", "all")
        if kind not in ("worker", "task", "actor", "all"):
            # Reject, don't default: a typo'd kind from the unvalidated
            # HTTP surface must not fan a sampling window out to every
            # process.
            return {"entries": [], "error":
                    f"unknown kind {kind!r} (worker|task|actor|all)"}
        ident = (payload.get("id") or "").lower()
        req = {
            "duration_s": float(payload.get("duration_s", 5.0)),
            "hz": float(payload.get("hz", 100.0)),
        }
        timeout = req["duration_s"] + float(
            payload.get("timeout_s", 10.0))
        from ray_tpu.util import profiler

        return await self._capture_fanout(
            kind, ident, "profile_capture", req, timeout,
            lambda: profiler.capture(**req))

    async def h_device_trace_capture_cluster(self, conn, payload):
        """Fan the ``device_trace_capture`` window out with the same
        worker|task|actor|all grammar as the host sampler. Each target
        runs one bounded jax.profiler window off its event loop and
        returns the parsed ops/steps/lanes plus the raw trace bytes;
        a dead peer or a per-process capture failure (concurrent
        capture, missing backend, oversized trace) comes back as a
        per-source error entry — the fan-out itself never fails."""
        payload = payload or {}
        kind = payload.get("kind", "all")
        if kind not in ("worker", "task", "actor", "all"):
            return {"entries": [], "error":
                    f"unknown kind {kind!r} (worker|task|actor|all)"}
        ident = (payload.get("id") or "").lower()
        req = {"duration_s": float(payload.get("duration_s", 2.0))}
        # Device captures carry jax import + trace flush on top of the
        # window itself, so the per-target deadline is roomier than the
        # host sampler's.
        timeout = req["duration_s"] + float(
            payload.get("timeout_s", 30.0))
        from ray_tpu.util import device_trace

        return await self._capture_fanout(
            kind, ident, "device_trace_capture", req, timeout,
            lambda: device_trace.capture(**req))

    async def h_debug_sched_state(self, conn, payload):
        """The scheduler's live waiting state, for the `why` explainer:
        every pending lease with its wait reason, node capacity, PG
        placement, and spawn backoffs."""
        sch = self.scheduler
        now = time.monotonic()
        pending = []
        for lease in sch.pending:
            spec = lease.spec
            strategy = spec.scheduling_strategy
            pending.append({
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "actor_id": spec.actor_id.hex() if spec.actor_id else None,
                "is_actor_creation": lease.is_actor_creation,
                "resources": lease.resources.to_dict(),
                "strategy": (type(strategy).__name__
                             if strategy is not None else "default"),
                "age_s": round(now - lease.queued_at, 3),
                "wait_reason": lease.wait_reason,
            })
        nodes = []
        for info in self.nodes_info.values():
            node = sch.nodes.get(info.node_id)
            nodes.append({
                "node_id": info.node_id.hex(),
                "state": info.state,
                "total": dict(info.resources),
                "available": (node.resources.available.to_dict()
                              if node and node.state == "ALIVE" else {}),
            })
        pgs = []
        for pg_id, info in self.placement_groups.items():
            placed = sum(1 for b in info.bundles if b.node_id is not None)
            pgs.append({
                "pg_id": pg_id.hex(), "state": info.state,
                "strategy": info.strategy, "name": info.name,
                "bundles": len(info.bundles), "bundles_placed": placed,
            })
        return {
            "pending": pending,
            "nodes": nodes,
            "pgs": pgs,
            "active_leases": len(sch.active_leases),
            "waiting_grants": {nid.hex(): len(q) for nid, q in
                               self._waiting_grants.items() if q},
            "spawn_backoff_s": {
                nid.hex(): round(until - now, 3)
                for nid, until in self._spawn_backoff_until.items()
                if until > now},
        }

    # ------------------------------------------------------------------

    async def shutdown(self):
        self._shutdown = True
        if self._pump_task:
            self._pump_task.cancel()
        for task in self._node_grace.values():
            task.cancel()
        self._node_grace.clear()
        if self.pool:
            self.pool.shutdown()
        self.shm.cleanup()


class LocalPeer:
    """In-process stand-in for a Connection (the driver inside the head
    process talks to HeadService without a socket)."""

    def __init__(self, notify_handler=None):
        self._notify_handler = notify_handler
        self.on_close = None
        self.closed = False
        self.state: Dict = {}

    async def notify(self, method: str, payload):
        if self._notify_handler:
            await self._notify_handler(method, payload)

    def notify_forget(self, method: str, payload=None):
        """Mirror rpc.Connection.notify_forget (pubsub publishes
        through this interface for the in-process driver too). There is
        no transport here — notify awaits the application handler
        directly — so handler bugs are LOGGED, not swallowed."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # interpreter teardown
            return None

        async def _run():
            try:
                await self.notify(method, payload)
            except Exception:
                logger.exception("in-process %s handler failed", method)

        return loop.create_task(_run())

    def close(self):
        self.closed = True
        if self.on_close:
            self.on_close(self)
