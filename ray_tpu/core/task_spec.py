"""Task / actor specifications — the unit shipped over the wire.

Reference: src/ray/common/task/task_spec.h:247 TaskSpecification and
common.proto TaskSpec. Specs are plain dataclasses pickled with the control
codec; argument values are pre-serialized (inline bytes for small args,
ObjectID references for large / owned objects).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass
class Address:
    """Location of a core worker's RPC endpoint."""

    host: str
    port: int
    worker_id_hex: str

    def key(self) -> Tuple[str, int]:
        return (self.host, self.port)


@dataclass
class TaskArg:
    """Either an inline serialized value or a reference.

    ``inline``: (metadata, inband, buffers) triple for pass-by-value.
    ``object_id`` + ``owner``: pass-by-reference; the executor resolves it
    from local stores or the owner.
    """

    inline: Optional[tuple] = None
    object_id: Optional[ObjectID] = None
    owner: Optional[Address] = None


class SchedulingStrategy:
    """Base for scheduling strategies (reference:
    python/ray/util/scheduling_strategies.py:15,41,135)."""


@dataclass
class DefaultSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class SpreadSchedulingStrategy(SchedulingStrategy):
    pass


@dataclass
class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    node_id_hex: str = ""
    soft: bool = False


@dataclass
class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    placement_group_id_hex: str = ""
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    name: str
    # Key of the exported function/class in the function table (GCS KV).
    function_key: str
    args: List[TaskArg]
    num_returns: int
    resources: Dict[str, float]
    owner: Address
    max_retries: int = 0
    retry_exceptions: bool = False
    scheduling_strategy: SchedulingStrategy = field(
        default_factory=DefaultSchedulingStrategy
    )
    runtime_env: Optional[dict] = None
    # Actor fields.
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seqno: int = -1  # actor-call ordering (reference:
    # sequential_actor_submit_queue.cc)
    concurrency_group: str = ""
    # Actor-creation fields.
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    is_async_actor: bool = False
    actor_name: str = ""  # named actor registration
    namespace: str = ""
    # Streaming-generator flow control: max chunks the executor may have
    # produced but the consumer not yet read before the generator body
    # is paused (credit-based; 0 = unbounded). Only meaningful when
    # num_returns == STREAMING.
    stream_window: int = 0

    def scheduling_key(self) -> tuple:
        """Groups tasks that can share a leased worker (reference:
        direct_task_transport.h:53 SchedulingKey = fn × resource shape ×
        runtime-env hash)."""
        return (
            self.function_key,
            tuple(sorted(self.resources.items())),
            repr(self.scheduling_strategy),
            repr(sorted((self.runtime_env or {}).items())),
        )

    STREAMING = -1  # num_returns sentinel: generator task, refs stream

    def return_object_ids(self) -> List[ObjectID]:
        if self.num_returns == self.STREAMING:
            return []
        return [
            ObjectID.for_task_return(self.task_id, i + 1)
            for i in range(self.num_returns)
        ]


@dataclass
class ActorInfo:
    """Actor-table row (reference: gcs.proto ActorTableData)."""

    actor_id: ActorID
    job_id: JobID
    state: str  # PENDING | ALIVE | RESTARTING | DEAD
    address: Optional[Address] = None
    node_id: Optional[NodeID] = None
    name: str = ""
    namespace: str = ""
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
    creation_spec: Optional[TaskSpec] = None


@dataclass
class NodeInfo:
    """Node-table row (reference: gcs.proto GcsNodeInfo)."""

    node_id: NodeID
    address: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    state: str = "ALIVE"  # ALIVE | SUSPECT (agent in death-grace) | DEAD
    # Remote hosts (node-agent processes): the agent's RPC address, which
    # doubles as the node's object fetch server for cross-node pulls.
    # None for head-host (virtual) nodes, whose store the head serves.
    agent_address: Optional[tuple] = None


@dataclass
class Bundle:
    resources: Dict[str, float]
    node_id: Optional[NodeID] = None  # assigned node after placement


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    state: str = "PENDING"  # PENDING | CREATED | REMOVED
    name: str = ""
