"""Unique identifiers for jobs, tasks, actors, objects, nodes, workers.

Mirrors the nested-ID design of the reference runtime (reference:
src/ray/common/id.h — JobID ⊂ ActorID ⊂ TaskID ⊂ ObjectID) so that lineage
can be recovered from an ID alone: an ObjectID embeds the TaskID that created
it plus a return/put index; a TaskID embeds the ActorID (or a nil actor) and
the JobID.

Layout (bytes, little-endian indices):
    JobID:    4 bytes
    ActorID:  12 bytes = 8 unique + JobID(4)
    TaskID:   16 bytes = 4 unique + ActorID(12)
    ObjectID: 20 bytes = TaskID(16) + 4-byte index
    NodeID / WorkerID / PlacementGroupID: 16 random bytes
"""

from __future__ import annotations

import os
import threading

JOB_ID_SIZE = 4
ACTOR_ID_SIZE = 12
TASK_ID_SIZE = 16
OBJECT_ID_SIZE = 20
UNIQUE_ID_SIZE = 16

_MAX_INDEX = 2**32 - 1


class _EntropyPool:
    """Buffered os.urandom: one syscall per ~16k ids instead of one per
    id (TaskID minting is on the task-submission hot path)."""

    def __init__(self):
        self._buf = b""
        self._off = 0
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        with self._lock:
            if self._off + n > len(self._buf):
                self._buf = os.urandom(65536)
                self._off = 0
            out = self._buf[self._off:self._off + n]
            self._off += n
            return out


_entropy = _EntropyPool()


class BaseID:
    __slots__ = ("_bytes",)
    SIZE = UNIQUE_ID_SIZE

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def __hash__(self):
        return hash(self._bytes)

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"


class JobID(BaseID):
    SIZE = JOB_ID_SIZE

    @classmethod
    def from_int(cls, value: int):
        return cls(value.to_bytes(JOB_ID_SIZE, "little"))

    def int_value(self) -> int:
        return int.from_bytes(self._bytes, "little")


class NodeID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class WorkerID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = UNIQUE_ID_SIZE


class ActorID(BaseID):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(ACTOR_ID_SIZE - JOB_ID_SIZE) + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID):
        return cls(b"\xff" * (ACTOR_ID_SIZE - JOB_ID_SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JOB_ID_SIZE:])


class TaskID(BaseID):
    SIZE = TASK_ID_SIZE

    _nil_actor_suffix: dict = {}  # job binary -> nil-actor suffix bytes

    @classmethod
    def for_normal_task(cls, job_id: JobID):
        suffix = cls._nil_actor_suffix.get(job_id._bytes)
        if suffix is None:
            suffix = (b"\xff" * (ACTOR_ID_SIZE - JOB_ID_SIZE)
                      + job_id._bytes)
            cls._nil_actor_suffix[job_id._bytes] = suffix
        return cls(_entropy.take(TASK_ID_SIZE - ACTOR_ID_SIZE) + suffix)

    @classmethod
    def for_actor_task(cls, actor_id: ActorID):
        return cls(_entropy.take(TASK_ID_SIZE - ACTOR_ID_SIZE)
                   + actor_id.binary())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID):
        # Deterministic creation-task id: zeros + actor id.
        return cls(b"\x00" * (TASK_ID_SIZE - ACTOR_ID_SIZE) + actor_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[-ACTOR_ID_SIZE:])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


class ObjectID(BaseID):
    SIZE = OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int):
        if not 0 < index <= _MAX_INDEX:
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # Put objects use the high bit of the index to avoid clashing with
        # return indices.
        return cls(task_id.binary() + (put_index | 0x80000000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_SIZE:], "little")

    def is_put(self) -> bool:
        return bool(self.index() & 0x80000000)

    def job_id(self) -> JobID:
        return self.task_id().job_id()


class IndexCounter:
    """Thread-safe monotonically increasing counter for put/return indices."""

    def __init__(self, start: int = 0):
        self._value = start
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
