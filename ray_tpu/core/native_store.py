"""ctypes bindings for the native shared-memory arena (cpp/tpustore).

The C++ store (cpp/tpustore/store.cc) is the plasma-equivalent data
plane: a single mmap'd arena per node with a free-extent allocator,
process-shared locking, and LRU eviction. This module builds the
library on first use (g++, cached by source hash) and exposes a thin
Python wrapper; payload parsing shares the flat layout of
object_store.ShmStore.pack so the two backends are wire-compatible.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
import weakref
from typing import Optional

logger = logging.getLogger(__name__)

_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "cpp", "tpustore")
_SRC = os.path.join(_CPP_DIR, "store.cc")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build_library() -> Optional[str]:
    """Compile store.cc into a cached .so keyed by source hash."""
    if not os.path.exists(_SRC):
        return None
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    build_dir = os.path.join(_CPP_DIR, "build")
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(build_dir, f"libtpustore_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    tmp = so_path + f".tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           _SRC, "-o", tmp, "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
        return so_path
    except Exception as e:
        logger.warning("tpustore build failed (%s); falling back to the "
                       "python shm store", e)
        return None


def get_library():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        so_path = _build_library()
        if so_path is None:
            _build_failed = True
            return None
        try:
            lib = _load_library(so_path)
        except Exception as e:
            logger.warning("tpustore load failed (%s); falling back to "
                           "the python shm store", e)
            _build_failed = True
            return None
        _lib = lib
        return _lib


def _load_library(so_path: str):
        lib = ctypes.CDLL(so_path)
        lib.ts_create.restype = ctypes.c_void_p
        lib.ts_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.ts_attach.restype = ctypes.c_void_p
        lib.ts_attach.argtypes = [ctypes.c_char_p]
        lib.ts_detach.argtypes = [ctypes.c_void_p]
        lib.ts_destroy.argtypes = [ctypes.c_char_p]
        lib.ts_alloc.restype = ctypes.c_int64
        lib.ts_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64,
                                 ctypes.POINTER(ctypes.c_uint64)]
        lib.ts_seal_idx.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.c_char_p, ctypes.c_int]
        lib.ts_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.ts_lookup_pin.restype = ctypes.c_int64
        lib.ts_lookup_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_uint64),
                                      ctypes.POINTER(ctypes.c_uint64)]
        lib.ts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ts_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ts_unpin.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ts_unpin_read.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ts_base.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.ts_base.argtypes = [ctypes.c_void_p]
        for fn in ("ts_used_bytes", "ts_num_objects", "ts_num_evicted",
                   "ts_capacity"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        return lib


TS_OK = 0
TS_EEXIST = -1
TS_ENOENT = -2
TS_EFULL = -3
TS_ESTATE = -5


class NativeArena:
    """One node's object arena (create in the head, attach in workers)."""

    def __init__(self, handle, lib, name: str, owner: bool):
        self._h = handle
        self._lib = lib
        self.name = name
        self._owner = owner
        self._base_addr = ctypes.cast(
            lib.ts_base(handle), ctypes.c_void_p).value
        # Serializes view finalizers against destroy() so a late unpin
        # can never touch an unmapped arena.
        self._detach_lock = threading.Lock()

    @classmethod
    def create(cls, name: str, capacity_bytes: int
               ) -> Optional["NativeArena"]:
        lib = get_library()
        if lib is None:
            return None
        h = lib.ts_create(name.encode(), capacity_bytes)
        if not h:
            return None
        return cls(h, lib, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> Optional["NativeArena"]:
        lib = get_library()
        if lib is None:
            return None
        h = lib.ts_attach(name.encode())
        if not h:
            return None
        return cls(h, lib, name, owner=False)

    def _view(self, offset: int, size: int) -> memoryview:
        """Zero-copy view into the arena."""
        buf = (ctypes.c_uint8 * size).from_address(
            self._base_addr + offset)
        return memoryview(buf).cast("B")

    def create_and_seal(self, key20: bytes, data,
                        pin_primary: bool = True) -> bool:
        """Returns False if the object already exists (idempotent) or was
        deleted while being written.

        ``pin_primary``: take the owner/primary eviction guard (in the
        same critical section as the seal) so LRU eviction never drops an
        object its owner still references; capacity overflow then
        surfaces as ObjectStoreFullError for the caller to spill to disk.
        """
        mv = memoryview(data).cast("B")
        off = ctypes.c_uint64()
        idx = self._lib.ts_alloc(self._h, key20, mv.nbytes,
                                 ctypes.byref(off))
        if idx == TS_EEXIST:
            return False
        if idx == TS_EFULL:
            from ray_tpu.exceptions import ObjectStoreFullError

            raise ObjectStoreFullError(
                f"object of {mv.nbytes} bytes does not fit in arena "
                f"({self.used_bytes()}/{self.capacity()} used)")
        if idx < 0:
            raise RuntimeError(f"ts_alloc failed: {idx}")
        self._view(off.value, mv.nbytes)[:] = mv
        rc = self._lib.ts_seal_idx(self._h, idx, key20,
                                   1 if pin_primary else 0)
        if rc == TS_ESTATE:
            # Deleted while being written (owner already released every
            # reference, so no consumer can exist); the arena freed it.
            return False
        if rc != TS_OK:
            raise RuntimeError(f"ts_seal failed: {rc}")
        return True

    def create_reserve(self, key20: bytes, nbytes: int):
        """Two-phase write: allocate a slot and return (idx, view) for
        the caller to fill in place (saves the intermediate packed-bytes
        copy of create_and_seal). Returns None if the key exists."""
        off = ctypes.c_uint64()
        idx = self._lib.ts_alloc(self._h, key20, nbytes, ctypes.byref(off))
        if idx == TS_EEXIST:
            return None
        if idx == TS_EFULL:
            from ray_tpu.exceptions import ObjectStoreFullError

            raise ObjectStoreFullError(
                f"object of {nbytes} bytes does not fit in arena "
                f"({self.used_bytes()}/{self.capacity()} used)")
        if idx < 0:
            raise RuntimeError(f"ts_alloc failed: {idx}")
        return idx, self._view(off.value, nbytes)

    def seal_reserved(self, idx: int, key20: bytes,
                      pin_primary: bool = True) -> bool:
        rc = self._lib.ts_seal_idx(self._h, idx, key20,
                                   1 if pin_primary else 0)
        if rc == TS_ESTATE:
            return False
        if rc != TS_OK:
            raise RuntimeError(f"ts_seal failed: {rc}")
        return True

    def _unpin_view(self, idx: int):
        # weakref.finalize callback: last view over this lookup died.
        with self._detach_lock:
            if self._h:
                self._lib.ts_unpin_read(self._h, idx)

    def lookup(self, key20: bytes, *, pin_for_read: bool = True
               ) -> Optional[memoryview]:
        """Zero-copy view of a sealed object.

        The default path takes an atomic read pin (ts_lookup_pin) and
        releases it when the last view/slice of the returned buffer is
        garbage-collected; a concurrent delete defers the free until
        then. ``pin_for_read=False`` skips pinning — only safe for
        transient reads that don't outlive the caller's frame.
        """
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if not pin_for_read:
            rc = self._lib.ts_lookup(self._h, key20, ctypes.byref(off),
                                     ctypes.byref(size))
            if rc != TS_OK:
                return None
            return self._view(off.value, size.value)
        idx = self._lib.ts_lookup_pin(self._h, key20, ctypes.byref(off),
                                      ctypes.byref(size))
        if idx < 0:
            return None
        mv = self._view(off.value, size.value)
        weakref.finalize(mv.obj, self._unpin_view, idx)
        return mv

    def contains(self, key20: bytes) -> bool:
        return bool(self._lib.ts_contains(self._h, key20))

    def pin(self, key20: bytes) -> bool:
        """Owner/primary eviction guard (not a read pin)."""
        return self._lib.ts_pin(self._h, key20) == TS_OK

    def unpin(self, key20: bytes) -> bool:
        return self._lib.ts_unpin(self._h, key20) == TS_OK

    def delete(self, key20: bytes):
        self._lib.ts_delete(self._h, key20)

    def used_bytes(self) -> int:
        return int(self._lib.ts_used_bytes(self._h))

    def num_objects(self) -> int:
        return int(self._lib.ts_num_objects(self._h))

    def num_evicted(self) -> int:
        return int(self._lib.ts_num_evicted(self._h))

    def capacity(self) -> int:
        return int(self._lib.ts_capacity(self._h))

    def destroy(self):
        with self._detach_lock:
            if self._h:
                h, self._h = self._h, None
                self._lib.ts_detach(h)
        if self._owner:
            self._lib.ts_destroy(self.name.encode())


# -- process-wide attachment (workers) --------------------------------------

_attached: Optional[NativeArena] = None
_attach_lock = threading.Lock()


def get_attached_arena() -> Optional[NativeArena]:
    """Attach to the node arena named by RAY_TPU_ARENA (set by the head
    for all spawned workers); None when the native store is disabled."""
    global _attached
    if _attached is not None:
        return _attached
    name = os.environ.get("RAY_TPU_ARENA")
    if not name:
        return None
    with _attach_lock:
        if _attached is None:
            _attached = NativeArena.attach(name)
        return _attached


def set_attached_arena(arena: Optional[NativeArena]):
    global _attached
    with _attach_lock:
        _attached = arena
