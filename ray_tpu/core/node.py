"""Node bootstrap — starts the head services in the driver process.

Reference: python/ray/_private/node.py:37 (Node supervisor) +
services.py:1421,1485 (process launchers). Unlike the reference, which
forks gcs_server and raylet daemons, this runtime hosts the control plane
on the driver's event-loop thread (head node) — worker processes are the
only forked processes. A future multi-host deployment runs the same
HeadService standalone (`python -m ray_tpu.core.head_main`).
"""

from __future__ import annotations

import logging
import os
import tempfile
import time
from typing import Dict, List, Optional

from ray_tpu.core import rpc
from ray_tpu.core.accelerators import TPUAcceleratorManager
from ray_tpu.core.config import Config
from ray_tpu.core.gcs import HeadService
from ray_tpu.core.ids import NodeID
from ray_tpu.core.object_store import ShmStore, default_capacity

logger = logging.getLogger(__name__)


def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          resources: Optional[Dict[str, float]] = None,
                          memory: Optional[float] = None) -> Dict[str, float]:
    out: Dict[str, float] = {}
    if num_cpus is None:
        out["CPU"] = float(os.cpu_count() or 1)
    else:
        out["CPU"] = float(num_cpus)
    if num_tpus is None:
        out.update(TPUAcceleratorManager.node_resources())
    elif num_tpus > 0:
        out["TPU"] = float(num_tpus)
    if memory is None:
        try:
            import psutil

            out["memory"] = float(psutil.virtual_memory().available)
        except Exception:
            out["memory"] = 4e9
    else:
        out["memory"] = float(memory)
    if resources:
        out.update({k: float(v) for k, v in resources.items()})
    return out


class HeadNode:
    """Owns the head's event loop, RPC server, shm store and services."""

    def __init__(self, config: Config, resources: Dict[str, float],
                 session_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.config = config
        self.host = host
        self.session_dir = session_dir or _make_session_dir()
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        # Driver-side spill path must match workers' (they inherit it
        # through the spawn env).
        os.environ["RAY_TPU_SESSION_DIR"] = self.session_dir
        # Data-plane listeners bind per the control plane's exposure.
        os.environ.setdefault(
            "RAY_TPU_BIND_HOST",
            "0.0.0.0" if host not in ("127.0.0.1", "localhost")
            else "127.0.0.1")
        if not resources.get("TPU"):
            # No chips on this node: keep accelerator site hooks (e.g. a
            # tunneled-TPU PJRT plugin registered via sitecustomize) out
            # of worker processes — they cost milliseconds per wakeup in
            # processes that never touch a chip (see scheduler.spawn).
            os.environ.setdefault("RAY_TPU_WORKER_PYTHONPATH_EXCLUDE",
                                  "axon_site")
        if config.object_spilling_dir:
            # Workers inherit through the spawn env; spill_dir() reads it.
            os.environ["RAY_TPU_OBJECT_SPILLING_DIR"] = \
                config.object_spilling_dir
        capacity = config.object_store_memory or default_capacity(
            config.object_store_memory_proportion
        )
        # Prefer the native C++ arena (cpp/tpustore); fall back to the
        # per-segment python store if the toolchain is unavailable.
        self.arena = None
        self.shm_store = None
        if config.use_native_object_store:
            from ray_tpu.core import native_store
            from ray_tpu.core.object_store import NativeShmStore

            name = f"rtpu_arena_{os.getpid()}_{int(time.time())}"
            self.arena = native_store.NativeArena.create(name, capacity)
            if self.arena is not None:
                os.environ["RAY_TPU_ARENA"] = name
                native_store.set_attached_arena(self.arena)
                self.shm_store = NativeShmStore(self.arena)
        if self.shm_store is None:
            self.shm_store = ShmStore(
                capacity,
                spill_threshold=config.object_spilling_threshold)
        self.loop_thread = rpc.EventLoopThread(name="ray-tpu-head")
        storage = None
        if config.gcs_fault_tolerance:
            from ray_tpu.core.gcs_storage import GcsStorage, storage_path

            try:
                storage = GcsStorage(storage_path(self.session_dir))
            except Exception:
                logger.exception("gcs persistence unavailable; running "
                                 "with in-memory state only")
        self.service = HeadService(config, self.shm_store, self.session_dir,
                                   host=host, storage=storage)
        self.server: Optional[rpc.Server] = None
        self.port: Optional[int] = None
        self.node_ids: List[NodeID] = []

        async def boot():
            self.server = rpc.Server(self.service.handlers(), name="head")
            bound = await self.server.start(host, port)
            self.service.attach(bound)
            return bound

        self.port = self.loop_thread.run(boot())
        self.default_node_id = self.add_node(resources)
        # Opt-in autoscaler monitor (reference: the Monitor head-node
        # process, autoscaler/_private/monitor.py:126): RAY_TPU_AUTOSCALER=1
        # + RAY_TPU_AUTOSCALER_CONFIG=<cluster config JSON>.
        self.monitor = None
        if os.environ.get("RAY_TPU_AUTOSCALER") == "1":
            cfg_path = os.environ.get("RAY_TPU_AUTOSCALER_CONFIG")
            if not cfg_path:
                logger.warning("RAY_TPU_AUTOSCALER=1 but no "
                               "RAY_TPU_AUTOSCALER_CONFIG; not starting")
            else:
                try:
                    self._start_monitor(cfg_path)
                except Exception:
                    logger.exception("autoscaler monitor failed to start")

    def _start_monitor(self, cfg_path: str):
        import json as _json

        from ray_tpu.autoscaler.monitor import (
            monitor_from_config_file,
            provider_from_config,
        )

        with open(cfg_path) as f:
            raw = _json.load(f)
        provider = provider_from_config(
            raw, head_address=f"{self.host}:{self.port}", head_node=self)

        def load_fn():
            return self.loop_thread.run(
                self.service.h_get_load(None, {}))

        self.monitor = monitor_from_config_file(
            cfg_path, provider, load_fn)
        self.service.autoscaler = self.monitor
        self.monitor.start()
        logger.info("autoscaler monitor running (interval %.1fs, %d "
                    "node types)", self.monitor.interval_s,
                    len(self.monitor.config.node_types))

    def add_node(self, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None) -> NodeID:
        """Add a (virtual) node — the fake-multi-node test substrate
        (reference: cluster_utils.Cluster.add_node, cluster_utils.py:174)."""

        async def go():
            return self.service.add_node(resources, labels)

        node_id = self.loop_thread.run(go())
        self.node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: NodeID):
        async def go():
            self.service.remove_node(node_id)

        self.loop_thread.run(go())
        if node_id in self.node_ids:
            self.node_ids.remove(node_id)

    def shutdown(self):
        if getattr(self, "monitor", None) is not None:
            try:
                self.monitor.stop()
            except Exception:
                pass
            self.monitor = None
        try:
            self.loop_thread.run(self.service.shutdown(), timeout=10)
        except Exception:
            logger.exception("head shutdown error")
        try:
            if self.server is not None:
                self.loop_thread.run(self.server.stop(), timeout=5)
        except Exception:
            pass
        self.loop_thread.stop()
        if self.arena is not None:
            from ray_tpu.core import native_store

            native_store.set_attached_arena(None)
            os.environ.pop("RAY_TPU_ARENA", None)
            self.arena = None


def _make_session_dir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    os.makedirs(base, exist_ok=True)
    path = os.path.join(base, f"session_{time.strftime('%Y%m%d_%H%M%S')}_"
                              f"{os.getpid()}")
    os.makedirs(path, exist_ok=True)
    return path
