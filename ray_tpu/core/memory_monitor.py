"""Node memory watchdog + worker-killing policy.

Reference: src/ray/common/memory_monitor.h:52 (cgroup/system usage
polling with a usage-fraction threshold) and the raylet's killing
policies (src/ray/raylet/worker_killing_policy_retriable_fifo.cc — kill
retriable work first, newest first, so long-running progress and
non-retriable work survive; worker_killing_policy_group_by_owner.cc).

A monitor runs on every host that spawns workers (the head and each
node agent). When used/limit crosses the threshold it kills ONE victim
worker per poll — retriable tasks before non-retriable, tasks before
actors, newest-started first within a class — records the reason, and
lets the runtime's normal worker-death cascade retry the task on
another worker. The owner's terminal error names the OOM kill instead
of a bare "worker died".
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)

_CGROUP_V2_USED = "/sys/fs/cgroup/memory.current"
_CGROUP_V2_LIMIT = "/sys/fs/cgroup/memory.max"
_CGROUP_V1_USED = "/sys/fs/cgroup/memory/memory.usage_in_bytes"
_CGROUP_V1_LIMIT = "/sys/fs/cgroup/memory/memory.limit_in_bytes"
# cgroup v1 reports "no limit" as a huge page-rounded sentinel.
_NO_LIMIT = 1 << 60


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    if raw == "max":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def node_memory() -> Tuple[int, int]:
    """(used_bytes, limit_bytes) for this host — cgroup limit when the
    container has one, /proc/meminfo otherwise. The test override
    RAY_TPU_MEMORY_LIMIT_BYTES narrows the limit so chaos tests can
    trigger pressure without exhausting the machine."""
    override = os.environ.get("RAY_TPU_MEMORY_LIMIT_BYTES")
    used = _read_int(_CGROUP_V2_USED)
    if used is None:
        used = _read_int(_CGROUP_V1_USED)
    limit = _read_int(_CGROUP_V2_LIMIT)
    if limit is None:
        limit = _read_int(_CGROUP_V1_LIMIT)
    if limit is not None and limit >= _NO_LIMIT:
        limit = None
    if used is None or limit is None:
        total = avail = None
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = int(line.split()[1]) * 1024
                    elif line.startswith("MemAvailable:"):
                        avail = int(line.split()[1]) * 1024
        except OSError:
            pass
        if total is None:
            return 0, 1
        if limit is None:
            limit = total
        if used is None:
            used = total - (avail or 0)
    if override:
        try:
            limit = int(override)
        except ValueError:
            pass
    return used, limit


def process_rss(pid: int) -> int:
    """Resident set size of one process (bytes); 0 if gone."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


@dataclass
class VictimCandidate:
    worker_id_hex: str
    pid: int
    retriable: bool       # current task has retries left (or is idle)
    is_actor: bool
    started_at: float     # when the current task/lease began


def pick_victim(cands: List[VictimCandidate]) -> Optional[VictimCandidate]:
    """Retriable tasks first, then non-retriable tasks, then actors;
    newest-started first within each class (the newest task has the
    least sunk progress — reference: retriable_fifo kills the least-
    recently-submitted retriable task; we invert to newest because a
    single-queue FIFO kill repeatedly starves the oldest task on a
    loaded node)."""
    cands = [c for c in cands if c.pid > 0]
    if not cands:
        return None

    def key(c: VictimCandidate):
        return (
            0 if (c.retriable and not c.is_actor) else
            1 if not c.is_actor else
            2 if c.retriable else 3,
            # Within a class, the process actually holding the memory
            # goes first — killing an idle bystander frees nothing and
            # the monitor would cycle through the pool.
            -process_rss(c.pid),
            -c.started_at,
        )

    return sorted(cands, key=key)[0]


class MemoryMonitor:
    """Poll loop body. The host embeds ``maybe_kill`` into its own
    event loop (asyncio task on the head, thread on the node agent)."""

    def __init__(self, threshold: float,
                 candidates: Callable[[], List[VictimCandidate]],
                 kill: Callable[[VictimCandidate, str], None],
                 min_kill_interval_s: float = 1.0):
        self.threshold = threshold
        self.candidates = candidates
        self.kill = kill
        self.min_kill_interval_s = min_kill_interval_s
        self._last_kill = 0.0

    def maybe_kill(self) -> Optional[str]:
        """One poll: returns the killed worker id hex, or None."""
        used, limit = node_memory()
        if limit <= 0 or used / limit < self.threshold:
            return None
        now = time.monotonic()
        if now - self._last_kill < self.min_kill_interval_s:
            return None  # give the previous kill time to free memory
        victim = pick_victim(self.candidates())
        if victim is None:
            logger.warning(
                "memory pressure (%.0f%% of %d bytes) but no killable "
                "worker", 100 * used / limit, limit)
            return None
        self._last_kill = now
        reason = (
            f"worker killed by the memory monitor: node memory usage "
            f"{used / (1 << 20):.0f} MiB exceeded "
            f"{100 * self.threshold:.0f}% of {limit / (1 << 20):.0f} MiB "
            f"(rss {process_rss(victim.pid) / (1 << 20):.0f} MiB). "
            f"Task was {'retriable' if victim.retriable else 'NOT retriable'}."
        )
        logger.warning("OOM kill: worker %s pid %d — %s",
                       victim.worker_id_hex[:12], victim.pid, reason)
        self.kill(victim, reason)
        return victim.worker_id_hex
