"""Worker process entry point + task executor.

Reference: python/ray/_private/workers/default_worker.py:282 (main loop) and
the Cython execute-task callback (_raylet.pyx:2009). A worker process:

1. connects to the head over the RPC transport and registers itself,
2. serves ``push_task`` / ``create_actor`` / ``cancel_task`` on its own
   server (direct calls from owners — the "direct task/actor transport"),
3. executes tasks on an executor (single thread for normal tasks; a thread
   pool for threaded actors with ``max_concurrency``; the event loop for
   async actors),
4. delivers small returns inline in the push reply and seals large returns
   into the node's shared-memory store,
5. exits when the head connection drops or on ``exit_worker``.

Actor call ordering: calls are executed in arrival order per caller
connection (reference: actor_scheduling_queue.cc seqno ordering) — the
transport preserves submission order on one TCP stream, and the executor
consumes its queue in FIFO order.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import sys
import threading
import time
import traceback
from typing import Optional

from ray_tpu import exceptions as exc
from ray_tpu.core import rpc, serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.core_worker import CoreWorker, HeadClient
from ray_tpu.core.ids import JobID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef, set_core_worker
from ray_tpu.core.serialization import SerializedObject
from ray_tpu.core.task_spec import TaskSpec, TaskType

logger = logging.getLogger(__name__)

# Live cProfile instances keyed by dump path (RAY_TPU_WORKER_PROFILE);
# dumped in main() before os._exit (atexit never runs there).
_PROFILERS: dict = {}


from ray_tpu.exceptions import ActorExitSignal  # noqa: E402 — see exceptions.py


class _StreamFlow:
    """Per-stream credit window state (producer side). ``sent`` advances
    as chunks go out, ``acked`` follows the consumer's read count
    (``stream_ack`` notifications); the generator body pauses while
    ``sent - acked >= window``. The threading.Condition serves executor-
    thread waiters; the asyncio.Event serves loop-side (async actor)
    waiters — acks arrive on the loop thread and poke both."""

    __slots__ = ("sent", "acked", "cond", "aevent")

    def __init__(self):
        self.sent = 0
        self.acked = 0
        self.cond = threading.Condition()
        self.aevent: Optional[asyncio.Event] = None


class Executor:
    """Runs tasks for this worker process."""

    def __init__(self, cw: CoreWorker):
        self.cw = cw
        self.actor_instance = None
        self.actor_spec: Optional[TaskSpec] = None
        self._queue: "asyncio.Queue" = asyncio.Queue()
        self._consumers: list = []
        self._started = False
        self._max_concurrency = 1
        self._is_async = False
        # task hex -> owner connection (streaming-generator item channel)
        self._stream_conns = {}
        # task hex -> _StreamFlow (flow-controlled streams only)
        self._stream_flow = {}
        # task hex -> executing thread ident (for cancellation)
        self._running_threads = {}
        self._cancelled_tasks = set()
        # Fast path (sync, max_concurrency=1): a dedicated thread pulls
        # from a plain queue and batches acks/completions onto the loop
        # with a single wakeup per burst — pipelined small tasks then
        # cost one self-pipe syscall per burst instead of two per task.
        self._sync_queue = None
        self._sync_thread = None
        self._loop = None
        self._pending_events: list = []
        self._events_lock = threading.Lock()
        self._events_wake = False
        # Result-delivery barrier: the executor thread must not start
        # the NEXT task until the previous task's reply bytes reached
        # the kernel — user code may os._exit() at any point, and a
        # process death must never destroy an already-computed sibling
        # result (at-most-once would silently burn the retry budget).
        self._delivered = threading.Event()
        self._delivered.set()
        # Deferred execution ack (normal tasks): the ack's only consumer
        # is the owner's free-retry decision on worker death, so a task
        # whose reply arrives never needed one — acking every tiny task
        # costs a syscall (and a cross-process wakeup) per task on the
        # critical path. Instead the loop acks only tasks still running
        # after ACK_DELAY; a death inside that window looks unstarted
        # and gets a free retry (bounded by the owner's free_retries
        # budget).
        self.ACK_DELAY = 0.02
        self._ack_slot = None  # [task_hex, conn, started, acked]
        self._ack_timer_running = False
        self._ack_idle_checks = 0

    def reconfigure(self, max_concurrency: int, is_async: bool):
        """Restart consumers with new settings (safe only while no task is
        in flight — i.e. right before an actor creation on a pooled worker
        that previously ran normal tasks)."""
        for t in self._consumers:
            t.cancel()
        self._consumers = []
        if self._sync_queue is not None:
            self._sync_queue.put(None)
            self._sync_queue = None
            self._sync_thread = None
        self._started = False
        self._delivered.set()  # never leave a new executor barriered
        self.ensure_started(max_concurrency, is_async)

    def ensure_started(self, max_concurrency: int = 1, is_async: bool = False):
        if self._started:
            return
        self._started = True
        self._max_concurrency = max(1, max_concurrency)
        self._is_async = is_async
        self._loop = asyncio.get_running_loop()
        if not is_async and self._max_concurrency == 1:
            import queue as _queue

            self._sync_queue = _queue.Queue()
            self._sync_thread = threading.Thread(
                target=self._sync_loop, name="task-executor", daemon=True)
            self._sync_thread.start()
            return
        n = self._max_concurrency if not is_async else 1
        for _ in range(n):
            self._consumers.append(
                asyncio.get_running_loop().create_task(self._consume())
            )

    # ---- sync fast path ----

    def _sync_loop(self):
        prof_path = os.environ.get("RAY_TPU_WORKER_PROFILE")
        if prof_path:
            import cProfile

            prof = cProfile.Profile()
            prof.enable()
            _PROFILERS[f"{prof_path}.{os.getpid()}.sync"] = prof
        import queue as _queue

        q = self._sync_queue
        while True:
            try:
                # Bounded get (lock-discipline audit): a lost shutdown
                # sentinel or a queue swapped mid-block must not strand
                # this thread forever — the Empty branch re-checks.
                item = q.get(timeout=1.0)
            except _queue.Empty:
                if q is not self._sync_queue:
                    return
                continue
            if item is None or q is not self._sync_queue:
                return
            spec, fut = item
            conn = self._stream_conns.get(spec.task_id.hex())
            is_normal = spec.task_type == TaskType.NORMAL_TASK
            tracked = (getattr(fut, "_rtpu_delivery_tracked", False)
                       and is_normal)
            # Delivery barrier (see __init__): the PREVIOUS task's reply
            # must hit the socket before this task's user code runs (it
            # may os._exit). An empty queue absorbs the handoff for free
            # — the loop drains while we block in q.get(). Replies that
            # went out through try_notify_sync never arm it.
            self._delivered.wait(timeout=10.0)
            epoch = self.cw.owner_notify_epoch
            # Arm the deferred ack (see __init__): the loop's ack timer
            # acks this task only if it is still running at ACK_DELAY.
            if is_normal and conn is not None:
                self._ack_slot = [spec.task_id.hex(), conn,
                                  time.monotonic(), False]
            try:
                result = self._execute_sync(spec)
            except BaseException as e:  # incl. ActorExitSignal
                self._ack_slot = None
                if tracked:
                    self._delivered.clear()
                self._post_event(("done", spec, fut, e))
            else:
                self._ack_slot = None
                # Reply fast path: put the bytes in the kernel from THIS
                # thread. Skipped when ordering could be violated —
                # streaming tasks (items ride the loop) or an add_borrow
                # queued during execution (epoch moved).
                sent = (
                    conn is not None
                    and spec.num_returns != TaskSpec.STREAMING
                    and self.cw.owner_notify_epoch == epoch
                    and conn.try_notify_sync("task_done", {
                        "task_id": spec.task_id.hex(), "reply": result})
                )
                if sent:
                    fut._rtpu_reply_sent = True
                elif tracked:
                    self._delivered.clear()
                self._post_event(("result", spec, fut, result))

    def ensure_ack_timer(self):
        """(loop thread) Start the deferred-ack scanner if idle. Runs
        every ACK_DELAY while tasks flow, stops itself after a few idle
        checks — ~50 wakeups/s while busy vs one syscall per task."""
        if self._ack_timer_running:
            return
        self._ack_timer_running = True
        self._ack_idle_checks = 0
        self._loop.call_later(self.ACK_DELAY, self._ack_check)

    def _ack_check(self):
        slot = self._ack_slot
        now = time.monotonic()
        if slot is not None and not slot[3] \
                and now - slot[2] >= self.ACK_DELAY:
            slot[3] = True
            try:
                slot[1].notify_nowait("task_accepted",
                                      {"task_id": slot[0]})
            except Exception:
                pass
        if slot is None:
            self._ack_idle_checks += 1
            if self._ack_idle_checks >= 3:
                self._ack_timer_running = False
                return
        else:
            self._ack_idle_checks = 0
        self._loop.call_later(self.ACK_DELAY, self._ack_check)

    def _post_event(self, event):
        with self._events_lock:
            self._pending_events.append(event)
            if self._events_wake:
                return
            self._events_wake = True
        self._loop.call_soon_threadsafe(self._drain_events)

    def _drain_events(self):
        with self._events_lock:
            events, self._pending_events = self._pending_events, []
            self._events_wake = False
        for kind, spec, fut, payload in events:
            if kind == "result":
                self._record_terminal(spec, payload)
                if not fut.done():
                    fut.set_result(payload)
            else:  # done-with-exception
                self.cw.record_task_event(
                    spec, "FINISHED"
                    if isinstance(payload, ActorExitSignal) else "FAILED")
                if not fut.done():
                    fut.set_exception(payload)

    @staticmethod
    async def _notify_quiet(conn, task_hex):
        try:
            await conn.notify("task_accepted", {"task_id": task_hex})
        except Exception:
            pass

    async def _ack_accepted(self, spec: TaskSpec):
        """Tell the owner execution is starting. Sent at dequeue time,
        not push receipt: with pipelined pushes, tasks still sitting in
        this queue when the worker dies provably never ran, and the
        missing ack lets the owner retry them for free. Normal tasks
        only — the free-retry decision is the ack's sole consumer."""
        if spec.task_type != TaskType.NORMAL_TASK:
            return
        conn = self._stream_conns.get(spec.task_id.hex())
        if conn is not None:
            await self._notify_quiet(conn, spec.task_id.hex())

    async def _consume(self):
        loop = asyncio.get_running_loop()
        sem = asyncio.Semaphore(self._max_concurrency)
        while True:
            spec, fut = await self._queue.get()
            await self._ack_accepted(spec)
            if self._is_async:
                await sem.acquire()

                async def run_async(spec=spec, fut=fut):
                    try:
                        result = await self._execute_async(spec)
                        self._record_terminal(spec, result)
                        if not fut.done():
                            fut.set_result(result)
                    except BaseException as e:  # incl. ActorExitSignal
                        self.cw.record_task_event(
                            spec, "FINISHED"
                            if isinstance(e, ActorExitSignal) else "FAILED")
                        if not fut.done():
                            fut.set_exception(e)
                    finally:
                        sem.release()

                loop.create_task(run_async())
            else:
                try:
                    result = await loop.run_in_executor(
                        None, self._execute_sync, spec
                    )
                    self._record_terminal(spec, result)
                    if not fut.done():
                        fut.set_result(result)
                except BaseException as e:  # incl. ActorExitSignal
                    self.cw.record_task_event(spec, "FAILED")
                    if not fut.done():
                        fut.set_exception(e)

    def _record_terminal(self, spec: TaskSpec, reply: dict):
        """Terminal state comes from where the result is produced, not
        from submit(): a cancelled awaiter must not mark a task that is
        still running (and may finish) as FAILED."""
        self.cw.record_task_event(
            spec, "FAILED" if reply.get("is_error") else "FINISHED")

    def submit_nowait(self, spec: TaskSpec, conn=None) -> "asyncio.Future":
        """Queue for execution and return the completion future — the
        hot push path attaches a done-callback instead of paying an
        awaiting coroutine per task. _stream_conns cleanup rides the
        future's callback chain."""
        fut = asyncio.get_running_loop().create_future()
        fut._rtpu_delivery_tracked = True  # see _sync_loop barrier
        self.cw.record_task_event(spec, "PENDING_EXECUTION")
        key = spec.task_id.hex()
        self._stream_conns[key] = conn
        fut.add_done_callback(
            lambda _f: self._stream_conns.pop(key, None))
        if self._sync_queue is not None:
            self._sync_queue.put((spec, fut))
        else:
            self._queue.put_nowait((spec, fut))
        return fut

    async def submit(self, spec: TaskSpec, conn=None) -> dict:
        fut = asyncio.get_running_loop().create_future()
        self.cw.record_task_event(spec, "PENDING_EXECUTION")
        self._stream_conns[spec.task_id.hex()] = conn
        try:
            if self._sync_queue is not None:
                self._sync_queue.put((spec, fut))
            else:
                await self._queue.put((spec, fut))
            return await fut
        finally:
            self._stream_conns.pop(spec.task_id.hex(), None)

    # ---- execution paths ----

    def _resolve_args(self, spec: TaskSpec):
        flat = []
        for arg in spec.args:
            if arg.inline is not None:
                metadata, inband, buffers = arg.inline
                flat.append(
                    serialization.deserialize(metadata, inband, buffers)
                )
            else:
                # Normal construction so the ref's destruction sends the
                # remove_ref matching the submitter's borrow registration.
                ref = ObjectRef(arg.object_id, arg.owner)
                flat.append(self.cw.get([ref])[0])
        kwargs = flat[-1] if flat else {}
        args = flat[:-1]
        return args, kwargs

    def _load_callable(self, spec: TaskSpec):
        # Sync cache hit first: the loop-thread round-trip below costs
        # two thread hops per call, which at tiny-task rates was the
        # single biggest executor cost (it paid even for functions
        # fetched thousands of calls ago).
        fn = self.cw._function_cache.get(spec.function_key)
        if fn is not None:
            return fn
        return self.cw.loop_thread.run(
            self.cw.fetch_function(spec.function_key)
        )

    @staticmethod
    def _apply_runtime_env(runtime_env: Optional[dict]):
        """Apply a task's runtime env; returns an undo callable.

        Reference: _private/runtime_env plugins. Supported here:
        env_vars (os.environ overlay), working_dir (chdir + sys.path),
        py_modules (sys.path), pip (venv-per-hash with a refcounted
        cache — runtime_env_pip.py). conda/container are gated out.
        """
        if not runtime_env:
            return lambda: None
        unsupported = set(runtime_env) - {"env_vars", "working_dir",
                                          "py_modules", "pip", "mpi"}
        if unsupported:
            raise exc.RayTpuError(
                f"unsupported runtime_env keys: {sorted(unsupported)}")
        pip_ctx = None
        pip_pkgs = runtime_env.get("pip")
        if pip_pkgs:
            from ray_tpu.core.runtime_env_pip import PipEnvContext

            try:
                pip_ctx = PipEnvContext(list(pip_pkgs))
                pip_ctx.__enter__()
            except Exception as e:
                raise exc.RuntimeEnvSetupError(
                    f"pip runtime env {pip_pkgs} failed: {e}")
        try:
            return Executor._apply_rest_of_runtime_env(runtime_env,
                                                       pip_ctx)
        except BaseException:
            # A failing env_vars/working_dir must not leak the pip
            # env's sys.path entry and cache refcount.
            if pip_ctx is not None:
                pip_ctx.__exit__(None, None, None)
            raise

    @staticmethod
    def _apply_rest_of_runtime_env(runtime_env: dict, pip_ctx):
        saved_env = {}
        added_paths = []
        saved_cwd = None
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = str(v)
        wd = runtime_env.get("working_dir")
        if wd:
            saved_cwd = os.getcwd()
            os.chdir(wd)
            if wd not in sys.path:
                sys.path.insert(0, wd)
                added_paths.append(wd)
        for mod_path in runtime_env.get("py_modules") or []:
            if mod_path not in sys.path:
                sys.path.insert(0, mod_path)
                added_paths.append(mod_path)

        def undo():
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            if saved_cwd is not None:
                try:
                    os.chdir(saved_cwd)
                except OSError:
                    pass
            for p in added_paths:
                try:
                    sys.path.remove(p)
                except ValueError:
                    pass
            if pip_ctx is not None:
                pip_ctx.__exit__(None, None, None)

        return undo

    def _execute_sync(self, spec: TaskSpec) -> dict:
        tid = spec.task_id
        self.cw.set_current_task_id(tid)
        self._running_threads[tid.hex()] = threading.get_ident()
        self.cw.record_task_event(spec, "RUNNING")
        # Live profiling plane: publish what this thread is executing so
        # sampled stacks are bucketed per task (util/profiler.py).
        from ray_tpu.util import profiler as _profiler

        _prof_token = _profiler.push_thread_context(
            task=tid.hex()[:16], name=spec.name or tid.hex()[:8],
            actor=spec.actor_id.hex()[:12] if spec.actor_id else "")
        undo_env = lambda: None  # noqa: E731
        try:
            if tid.hex() in self._cancelled_tasks:
                raise exc.TaskCancelledError(f"task {spec.name} cancelled")
            undo_env = self._apply_runtime_env(spec.runtime_env)
            args, kwargs = self._resolve_args(spec)
            trace_ctx = (kwargs.pop("_rtpu_trace_ctx", None)
                         if isinstance(kwargs, dict) else None)
            if trace_ctx is not None:
                # The carrier's presence proves the driver enabled
                # tracing — don't depend on env-flag inheritance (warm
                # workers / agent-spawned workers predate the driver).
                from ray_tpu.util import tracing as _tracing

                _tracing.setup_tracing("ray_tpu.worker")
            mpi_cfg = (spec.runtime_env or {}).get("mpi")
            if mpi_cfg and spec.task_type != TaskType.NORMAL_TASK:
                # Actors hold their env for life and never re-gang;
                # silently running un-ganged would betray code that
                # assumes N ranks (PARITY.md: normal tasks only).
                raise exc.RayTpuError(
                    "mpi runtime env supports normal tasks only")
            if spec.task_type == TaskType.NORMAL_TASK:
                fn = self._load_callable(spec)
                if mpi_cfg:
                    # MPI runtime env: the function body runs on rank 0
                    # of a freshly launched gang (runtime_env_mpi.py).
                    from ray_tpu.core.runtime_env_mpi import run_under_mpi

                    if spec.num_returns == TaskSpec.STREAMING:
                        raise exc.RayTpuError(
                            "mpi runtime env does not support "
                            "streaming generators")
                    fn_inner = fn
                    fn = (lambda *a, **kw:
                          run_under_mpi(mpi_cfg, fn_inner, a, kw))
                if spec.num_returns == TaskSpec.STREAMING:
                    if trace_ctx is not None:
                        with _tracing.task_span(spec.name, trace_ctx):
                            return self._execute_streaming(
                                spec, fn, args, kwargs)
                    return self._execute_streaming(spec, fn, args, kwargs)
                if trace_ctx is not None:
                    with _tracing.task_span(spec.name, trace_ctx):
                        value = fn(*args, **kwargs)
                else:
                    value = fn(*args, **kwargs)
            elif spec.task_type == TaskType.ACTOR_CREATION_TASK:
                cls = self._load_callable(spec)
                self.actor_instance = cls(*args, **kwargs)
                self.actor_spec = spec
                value = None
            else:  # ACTOR_TASK
                if self.actor_instance is None:
                    raise exc.ActorDiedError(
                        spec.actor_id.hex() if spec.actor_id else "",
                        "actor instance missing",
                    )
                if spec.method_name == "__rtpu_channel_loop__":
                    # Compiled-DAG execution loop: pins this actor's
                    # execution thread to its channels until torn down
                    # (reference: compiled_dag_node.py's do_exec_tasks
                    # loop on the actor).
                    from ray_tpu.experimental.compiled_dag import (
                        run_channel_loop,
                    )

                    value = run_channel_loop(self.actor_instance,
                                             args[0])
                else:
                    method = getattr(self.actor_instance,
                                     spec.method_name)
                    if spec.num_returns == TaskSpec.STREAMING:
                        # Streaming over the actor RPC lane: the method
                        # must hand back a generator; each yield ships
                        # as a stream_item exactly like a streaming
                        # normal task.
                        out = method(*args, **kwargs)
                        if not hasattr(out, "__next__"):
                            raise TypeError(
                                f"actor method {spec.method_name!r} "
                                "called with num_returns='streaming' "
                                "must return a generator, got "
                                f"{type(out).__name__}")
                        return self._stream_items(spec, out)
                    value = method(*args, **kwargs)
            return self._package_returns(spec, value)
        except ActorExitSignal:
            raise
        except exc.TaskCancelledError as e:
            return self._package_error(spec, e)
        except BaseException as e:  # noqa: B036 — tasks isolate all failures
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            return self._package_error(spec, e)
        finally:
            # Actors keep their runtime env for life (the dedicated
            # worker is theirs); plain tasks restore the pristine env so
            # a reused worker doesn't leak one task's env into the next.
            if spec.task_type == TaskType.NORMAL_TASK:
                undo_env()
            _profiler.pop_thread_context(_prof_token)
            self._running_threads.pop(tid.hex(), None)
            self._cancelled_tasks.discard(tid.hex())
            self.cw.set_current_task_id(None)

    async def _execute_async(self, spec: TaskSpec) -> dict:
        """Async-actor path: methods may be coroutines."""
        self.cw.set_current_task_id(spec.task_id)
        self.cw.record_task_event(spec, "RUNNING")
        # Token-based context (not LIFO): interleaved coroutines share
        # this loop thread, so each removes exactly its own entry. A
        # sampled loop-thread stack attributes to the most recently
        # entered task — approximate under concurrency, exact when one
        # method (a jit warmup, a blocking build) pins the loop.
        from ray_tpu.util import profiler as _profiler

        _prof_token = _profiler.push_thread_context(
            task=spec.task_id.hex()[:16],
            name=spec.name or spec.task_id.hex()[:8],
            actor=spec.actor_id.hex()[:12] if spec.actor_id else "")
        try:
            args, kwargs = await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._resolve_args(spec)
            )
            if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                # The actor owns this worker; its runtime env applies
                # for the worker's lifetime.
                self._apply_runtime_env(spec.runtime_env)
                # NB: must await (not _load_callable) — blocking the loop
                # here would deadlock the worker.
                cls = await self.cw.fetch_function(spec.function_key)
                self.actor_instance = cls(*args, **kwargs)
                self.actor_spec = spec
                value = None
            else:
                method = getattr(self.actor_instance, spec.method_name)
                value = method(*args, **kwargs)
                if asyncio.iscoroutine(value):
                    value = await value
                if spec.num_returns == TaskSpec.STREAMING:
                    return await self._astream_items(spec, value)
            return self._package_returns(spec, value)
        except BaseException as e:  # noqa: B036
            if isinstance(e, (KeyboardInterrupt, SystemExit, ActorExitSignal)):
                raise
            return self._package_error(spec, e)
        finally:
            # Mirror _execute_sync's cleanup: stream cancellation is the
            # ROUTINE terminal path for serve streams (every client
            # disconnect), so a leftover entry per cancelled task would
            # grow this set unboundedly on long-lived async replicas.
            _profiler.pop_thread_context(_prof_token)
            self._cancelled_tasks.discard(spec.task_id.hex())
            self.cw.set_current_task_id(None)

    # ---- return packaging ----

    # ---- streaming generators ----

    def on_stream_ack(self, payload: dict) -> None:
        """(loop thread) The consumer read up to ``read`` items of a
        flow-controlled stream; reopen the producer's credit window."""
        flow = self._stream_flow.get(payload.get("task_id"))
        if flow is None:
            return
        with flow.cond:
            flow.acked = max(flow.acked, int(payload.get("read", 0)))
            flow.cond.notify_all()
            if flow.aevent is not None:
                flow.aevent.set()

    def _stream_payload(self, spec: TaskSpec, count: int, value,
                        ack: bool) -> dict:
        object_id = ObjectID.for_task_return(spec.task_id, count + 1)
        obj = serialization.serialize(value)
        ret = self._store_return(object_id, obj)
        payload = {"task_id": spec.task_id.hex(), **ret}
        if ack:
            # Tells the owner this stream is flow-controlled: every
            # consumed item must be acked with the read count.
            payload["ack"] = True
        return payload

    def _check_stream_cancel(self, spec: TaskSpec):
        if spec.task_id.hex() in self._cancelled_tasks:
            raise exc.TaskCancelledError(f"stream {spec.name} cancelled")

    def _wait_for_credit(self, spec: TaskSpec, flow: _StreamFlow,
                         window: int):
        """(executor thread) Block while the credit window is closed;
        polls so a consumer-side cancel still interrupts the wait."""
        while True:
            with flow.cond:
                if flow.sent - flow.acked < window:
                    return
                flow.cond.wait(timeout=0.05)
            self._check_stream_cancel(spec)

    def _stream_error_reply(self, spec: TaskSpec, error: BaseException,
                            count: int) -> dict:
        err = serialization.serialize_error(error, task_name=spec.name)
        return {
            "returns": [], "is_error": True, "stream_count": count,
            "error_payload": {
                "metadata": err.metadata, "inband": err.inband,
                "buffers": [bytes(memoryview(b)) for b in err.buffers],
            },
        }

    def _stream_items(self, spec: TaskSpec, iterator) -> dict:
        """(executor thread) Drive a sync generator as a stream: each
        yielded value becomes its own return object, reported to the
        owner over the push connection as it is produced (reference:
        streaming generator returns, task_manager.h:98). The final reply
        carries the item count. ``spec.stream_window > 0`` enables
        credit-based backpressure: the body pauses once that many chunks
        are produced-but-unread, so a slow consumer bounds the
        producer's buffering instead of OOMing it."""
        conn = self._stream_conns.get(spec.task_id.hex())
        if conn is None:
            raise exc.RayTpuError("streaming task has no owner channel")
        window = max(0, getattr(spec, "stream_window", 0) or 0)
        flow = None
        if window:
            flow = _StreamFlow()
            self._stream_flow[spec.task_id.hex()] = flow
        count = 0
        try:
            for value in iterator:
                payload = self._stream_payload(spec, count, value,
                                               ack=window > 0)
                # Ordered delivery: notifications ride the same TCP
                # stream as the final reply, which is sent only after
                # this method returns.
                self.cw.loop_thread.submit(
                    conn.notify("stream_item", payload))
                count += 1
                if flow is not None:
                    with flow.cond:
                        flow.sent = count
                    self._wait_for_credit(spec, flow, window)
                self._check_stream_cancel(spec)
        except BaseException as e:  # noqa: B036
            if isinstance(e, (KeyboardInterrupt, SystemExit,
                              ActorExitSignal)):
                raise
            self._close_iter_quietly(iterator)
            return self._stream_error_reply(spec, e, count)
        finally:
            if flow is not None:
                self._stream_flow.pop(spec.task_id.hex(), None)
        return {"returns": [], "is_error": False, "stream_count": count}

    @staticmethod
    def _close_iter_quietly(iterator):
        close = getattr(iterator, "close", None)
        if callable(close):
            try:
                close()
            except Exception:
                pass

    def _execute_streaming(self, spec: TaskSpec, fn, args, kwargs) -> dict:
        return self._stream_items(spec, fn(*args, **kwargs))

    async def _await_credit(self, spec: TaskSpec, flow: _StreamFlow,
                            window: int):
        """(loop) Async-actor variant of ``_wait_for_credit``; acks
        arrive on this same loop thread, so the event wake is race-free."""
        while True:
            with flow.cond:
                if flow.sent - flow.acked < window:
                    return
                if flow.aevent is None:
                    flow.aevent = asyncio.Event()
                flow.aevent.clear()
                event = flow.aevent
            self._check_stream_cancel(spec)
            try:
                await asyncio.wait_for(event.wait(), timeout=0.1)
            except asyncio.TimeoutError:
                pass

    async def _astream_items(self, spec: TaskSpec, source) -> dict:
        """(loop) Async-actor streaming: the method produced an async
        generator (or a plain generator — iterated inline). Mirrors
        ``_stream_items`` including the credit window; cancellation is
        polled between chunks so a consumer disconnect actually stops
        the generator body."""
        conn = self._stream_conns.get(spec.task_id.hex())
        if conn is None:
            raise exc.RayTpuError("streaming task has no owner channel")
        if hasattr(source, "__anext__"):
            aiter_src = source
        elif hasattr(source, "__next__"):
            # A plain generator on an async actor: iterated inline on
            # the loop (the user chose sync code in an async context).
            async def _lift(it=source):
                for v in it:
                    yield v

            aiter_src = _lift()
        else:
            return self._package_error(spec, TypeError(
                f"method {spec.method_name!r} with "
                f"num_returns='streaming' must return a generator or "
                f"async generator, got {type(source).__name__}"))
        window = max(0, getattr(spec, "stream_window", 0) or 0)
        flow = None
        if window:
            flow = _StreamFlow()
            self._stream_flow[spec.task_id.hex()] = flow
        tid_hex = spec.task_id.hex()
        count = 0
        try:
            while True:
                self._check_stream_cancel(spec)
                nxt = asyncio.ensure_future(aiter_src.__anext__())
                while not nxt.done():
                    await asyncio.wait({nxt}, timeout=0.25)
                    if tid_hex in self._cancelled_tasks and not nxt.done():
                        nxt.cancel()
                        try:
                            await nxt
                        except BaseException:  # noqa: B036 — cancel race
                            pass
                        raise exc.TaskCancelledError(
                            f"stream {spec.name} cancelled")
                try:
                    # lint: allow-blocking(asyncio Task.result() after the done()-loop above — never blocks)
                    value = nxt.result()
                except StopAsyncIteration:
                    break
                payload = self._stream_payload(spec, count, value,
                                               ack=window > 0)
                await conn.notify("stream_item", payload)
                count += 1
                if flow is not None:
                    with flow.cond:
                        flow.sent = count
                    await self._await_credit(spec, flow, window)
        except BaseException as e:  # noqa: B036
            if isinstance(e, (KeyboardInterrupt, SystemExit,
                              ActorExitSignal)):
                raise
            await self._aclose_quietly(aiter_src)
            return self._stream_error_reply(spec, e, count)
        finally:
            if flow is not None:
                self._stream_flow.pop(tid_hex, None)
        return {"returns": [], "is_error": False, "stream_count": count}

    @staticmethod
    async def _aclose_quietly(aiter_src):
        aclose = getattr(aiter_src, "aclose", None)
        if aclose is None:
            Executor._close_iter_quietly(aiter_src)
            return
        try:
            await aclose()
        except Exception:
            pass

    def _package_returns(self, spec: TaskSpec, value) -> dict:
        n = spec.num_returns
        returns = []
        if n == 0:
            values = []
        elif n == 1:
            values = [value]
        else:
            if not isinstance(value, (tuple, list)) or len(value) != n:
                raise ValueError(
                    f"task {spec.name} declared num_returns={n} but returned "
                    f"{type(value).__name__}"
                )
            values = list(value)
        for i, v in enumerate(values):
            object_id = ObjectID.for_task_return(spec.task_id, i + 1)
            obj = serialization.serialize(v)
            returns.append(self._store_return(object_id, obj))
        return {"returns": returns, "is_error": False}

    def _package_error(self, spec: TaskSpec, error: BaseException) -> dict:
        logger.info("task %s failed: %r", spec.name, error)
        if spec.num_returns == TaskSpec.STREAMING:
            # A streaming task that failed before (or outside) its
            # generator body still must close the owner's stream, or
            # iteration would hang forever with the error lost.
            return self._stream_error_reply(spec, error, 0)
        obj = serialization.serialize_error(error, task_name=spec.name)
        returns = []
        for object_id in spec.return_object_ids():
            returns.append(self._store_return(object_id, obj))
        return {"returns": returns, "is_error": True}

    def _store_return(self, object_id: ObjectID, obj: SerializedObject) -> dict:
        if obj.total_size() > self.cw.config.max_direct_call_object_size:
            size = self.cw._seal_to_shm(object_id, obj)
            self.cw.loop_thread.submit(
                self.cw.head.call(
                    "object_sealed",
                    {"object_id": object_id.hex(), "size": size,
                     "node_id": self.cw.node_id_hex},
                )
            )
            return {"object_id": object_id.binary(), "in_plasma": True}
        return {
            "object_id": object_id.binary(),
            "in_plasma": False,
            "metadata": obj.metadata,
            "inband": obj.inband,
            "buffers": [bytes(memoryview(b)) for b in obj.buffers],
        }

    # ---- cancellation ----

    def cancel(self, task_id_hex: str, force: bool):
        self._cancelled_tasks.add(task_id_hex)
        ident = self._running_threads.get(task_id_hex)
        if ident is not None:
            # Inject TaskCancelledError into the executing thread
            # (reference: worker interrupt on CancelTask RPC).
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident),
                ctypes.py_object(exc.TaskCancelledError),
            )


async def _amain():
    # Restore documented JAX env semantics: some PJRT plugin site hooks
    # (e.g. the tunneled-TPU axon plugin) call
    # jax.config.update("jax_platforms", ...) at interpreter start,
    # which silently overrides JAX_PLATFORMS. The driver's platform
    # choice must hold in its workers — a CPU-only test cluster must not
    # route every worker's jax dispatch through a tunneled TPU.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:
            pass
    config = get_config()
    head_host = os.environ["RAY_TPU_HEAD_HOST"]
    head_port = int(os.environ["RAY_TPU_HEAD_PORT"])
    worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])

    from ray_tpu.core.rpc import EventLoopThread

    # The running loop belongs to this main coroutine; CoreWorker needs a
    # loop_thread facade over it.
    class _LoopFacade:
        def __init__(self, loop):
            self.loop = loop

        def run(self, coro, timeout=None):
            # Called from executor threads only (never from the loop itself).
            fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
            return fut.result(timeout)

        def submit(self, coro):
            return asyncio.run_coroutine_threadsafe(coro, self.loop)

    loop = asyncio.get_running_loop()
    loop_thread = _LoopFacade(loop)
    # Event-loop lag probe: the worker's loop serves task dispatch,
    # replies, and replica loops — its lag is the per-process "am I
    # starved" fact the observatory aggregates cluster-wide.
    try:
        from ray_tpu.util import rpc_stats

        rpc_stats.install_probe(loop, "worker-loop")
    except Exception:  # lint: allow-silent(lag probe is decoration; the worker must boot regardless)
        pass

    # Job id is discovered from the first task spec; start with a nil-ish job.
    cw = CoreWorker(
        config=config,
        loop_thread=loop_thread,
        head=None,  # set after connect
        job_id=JobID.from_int(0),
        worker_id=worker_id,
        mode="worker",
        host=os.environ.get("RAY_TPU_BIND_HOST", "127.0.0.1"),
        advertise_host=os.environ.get("RAY_TPU_ADVERTISE_HOST"),
    )
    executor = Executor(cw)
    cw.executor = executor
    set_core_worker(cw)

    exit_event = asyncio.Event()

    async def h_push_task(conn, payload):
        spec: TaskSpec = serialization.loads_control(payload["spec"])
        # Actor executors are configured by create_actor (reconfigure);
        # this covers plain tasks on a fresh worker. The execution-start
        # ack (task_accepted) is sent by the executor at dequeue time.
        executor.ensure_started()
        try:
            return await executor.submit(spec, conn)
        except ActorExitSignal:
            out = {"returns": [], "is_error": False}
            asyncio.get_running_loop().create_task(_graceful_actor_exit())
            return out

    async def h_push_tasks(conn, payload):
        """Batched push (a notification): N specs arrive in one frame;
        each task's result streams back as its own ``task_done``
        notification the moment it finishes. Batching amortizes the RPC
        envelope + loop wakeups that dominate tiny-task throughput,
        while per-task completion keeps results independent — task B in
        a batch may resolve an owner-held ref produced by task A of the
        same batch, so replies must NOT wait for the batch (reference:
        one PushTask RPC per task, direct_task_transport.h:63; here one
        frame carries many)."""
        # A notification handler's exceptions vanish in rpc._dispatch —
        # the owner would hang on every task in the batch. Every failure
        # mode must therefore surface as a task_done carrying an error
        # reply (a spec that cannot even be deserialized is a protocol
        # bug; it is logged loudly and the rest of the batch proceeds).
        specs = []
        for blob in payload["specs"]:
            try:
                specs.append(serialization.loads_control(blob))
            except Exception as decode_err:  # noqa: BLE001
                logging.getLogger(__name__).exception(
                    "undecodable task spec in push_tasks batch")
                # push_tasks is a notification — without a task_done the
                # owner waits on this task forever. Name the task from
                # the raw blob if at all possible; failing that, close
                # the connection so the owner's _fail_worker_conn path
                # fails everything outstanding instead of hanging.
                tid_hex = serialization.spec_task_id_from_blob(blob)
                if tid_hex is not None:
                    try:
                        conn.notify_nowait("task_done", {
                            "task_id": tid_hex,
                            "reply": {"spec_decode_error":
                                      f"{type(decode_err).__name__}: "
                                      f"{decode_err}"}})
                    except Exception:
                        pass
                else:
                    # Abandon the whole batch: once the conn closes the
                    # owner fails-and-retries everything outstanding, so
                    # running the decodable remainder here would execute
                    # those tasks twice.
                    asyncio.get_running_loop().create_task(conn.close())
                    return
        executor.ensure_started()

        def finish(spec, fut):
            if getattr(fut, "_rtpu_reply_sent", False):
                return  # reply already in the kernel (executor fast path)
            try:
                e = fut.exception()
            except asyncio.CancelledError:
                # A real error reply: empty returns would leave the
                # owner's return ObjectIDs unresolvable (get() hangs).
                reply = executor._package_error(
                    spec, exc.TaskCancelledError(
                        f"task {spec.name} cancelled"))
            else:
                if e is None:
                    reply = fut.result()
                elif isinstance(e, ActorExitSignal):
                    asyncio.get_running_loop().create_task(
                        _graceful_actor_exit())
                    reply = {"returns": [], "is_error": False}
                else:
                    reply = executor._package_error(spec, e)
            try:
                conn.notify_nowait("task_done", {
                    "task_id": spec.task_id.hex(), "reply": reply})
                # Hand the bytes to the kernel NOW: the executor thread
                # is barriered on delivery before it runs the next task
                # (which may os._exit and take the outbuf with it).
                conn._flush()
            except Exception:
                pass  # owner gone; its failure handling owns the task
            _release_delivery_barrier(conn)

        def _release_delivery_barrier(conn):
            """Release the executor only once the reply's bytes left
            user space — under backpressure the transport buffers, and
            an os._exit would still destroy a buffered reply."""
            if conn.closed or conn.write_buffer_empty():
                executor._delivered.set()
                return
            asyncio.get_running_loop().call_later(
                0.005, _release_delivery_barrier, conn)

        import functools

        for spec in specs:
            fut = executor.submit_nowait(spec, conn)
            fut.add_done_callback(functools.partial(finish, spec))
        executor.ensure_ack_timer()
        return {"ok": True}

    async def h_create_actor(conn, payload):
        spec: TaskSpec = serialization.loads_control(payload["spec"])
        cw.job_id = spec.job_id
        executor.reconfigure(
            max_concurrency=spec.max_concurrency,
            is_async=spec.is_async_actor,
        )
        try:
            result = await executor.submit(spec)
        except BaseException as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if result.get("is_error"):
            # Surface the traceback as the death cause.
            ret = result["returns"][0] if result["returns"] else None
            detail = ""
            if ret is not None and not ret.get("in_plasma"):
                try:
                    err = serialization.deserialize_no_raise(
                        ret["metadata"], ret["inband"], ret.get("buffers", [])
                    )[0]
                    detail = str(err)
                except Exception:
                    detail = "actor __init__ failed"
            return {"ok": False, "error": detail}
        return {"ok": True}

    async def _graceful_actor_exit():
        if executor.actor_spec is not None:
            try:
                await head_conn.call("actor_exited", {
                    "actor_id": executor.actor_spec.actor_id.hex(),
                })
            except Exception:
                pass
        exit_event.set()

    async def h_cancel_task(conn, payload):
        executor.cancel(payload["task_id"], payload.get("force", False))
        return {"ok": True}

    def h_stream_ack(conn, payload):
        # Sync notification handler (rpc fast path): consumer-side read
        # acks reopening a flow-controlled stream's credit window.
        executor.on_stream_ack(payload or {})

    async def h_exit_worker(conn, payload):
        exit_event.set()
        return {"ok": True}

    port = await cw.start_server(extra_handlers={
        "push_task": h_push_task,
        "push_tasks": h_push_tasks,
        "create_actor": h_create_actor,
        "cancel_task": h_cancel_task,
        "stream_ack": h_stream_ack,
        "exit_worker": h_exit_worker,
    })

    head_conn = await rpc.connect(
        head_host, head_port, {
            **cw.handlers(),
            "create_actor": h_create_actor,
            "exit_worker": h_exit_worker,
        },
        name="worker-head",
    )
    cw.head = HeadClient(conn=head_conn)
    head_conn.on_close = lambda c: exit_event.set()

    reply = await head_conn.call("register_worker", {
        "worker_id": worker_id.hex(),
        # Remote-host workers advertise their host's address so owners on
        # other machines can reach the task server (head-host default).
        "host": os.environ.get("RAY_TPU_ADVERTISE_HOST", "127.0.0.1"),
        "port": port,
        "pid": os.getpid(),
    })
    if not reply.get("ok"):
        logger.error("worker registration rejected: %s", reply)
        return 1

    await exit_event.wait()
    return 0


def main():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s worker %(name)s: %(message)s",
    )
    # SIGUSR1 dumps all thread stacks to stderr (the worker log) — the
    # on-demand profiling hook (reference: ray stack / py-spy dump via
    # dashboard/modules/reporter/profile_manager.py).
    import faulthandler
    import signal as _signal

    try:
        faulthandler.register(_signal.SIGUSR1, all_threads=True)
    except (AttributeError, ValueError):
        pass
    # Crash postmortem: an unhandled exception anywhere in the process
    # flushes the flight-recorder ring + all-thread stacks to
    # <session>/logs/postmortem-<pid>.json before the interpreter dies.
    from ray_tpu.util import flight_recorder

    flight_recorder.install_crash_handler()
    # Live profiling plane: the always-on low-Hz sampler when
    # profiler_continuous_enabled is set (on-demand captures need no
    # standing thread — they are served by the profile_capture RPC).
    from ray_tpu.util import profiler as _profiler

    _profiler.maybe_start_continuous()
    # DEPRECATED startup-only cProfile hook: RAY_TPU_WORKER_PROFILE
    # predates the live profiling plane (`ray_tpu profile ...` /
    # profile_capture RPC) and only covers process lifetime with
    # cProfile's tracing overhead. Kept for raw callgrind-style stats;
    # prefer the sampler for everything else.
    prof_path = os.environ.get("RAY_TPU_WORKER_PROFILE")
    if prof_path:
        import cProfile

        _prof = cProfile.Profile()
        _prof.enable()
        _PROFILERS[f"{prof_path}.{os.getpid()}.loop"] = _prof
    sample_path = os.environ.get("RAY_TPU_WORKER_SAMPLE")
    if sample_path:
        # Wall-clock sampler surviving SIGKILL: collapsed stacks of all
        # threads, rewritten every 2s (py-spy-style, stdlib-only).
        def _sampler():
            import collections
            import time as _t

            counts: dict = collections.Counter()
            last_dump = _t.monotonic()
            while True:
                _t.sleep(0.002)
                for tid, frame in sys._current_frames().items():
                    stack = []
                    f = frame
                    while f is not None and len(stack) < 30:
                        stack.append(
                            f"{f.f_code.co_filename.rsplit('/', 1)[-1]}"
                            f":{f.f_code.co_name}")
                        f = f.f_back
                    counts[";".join(reversed(stack))] += 1
                if _t.monotonic() - last_dump > 2:
                    last_dump = _t.monotonic()
                    with open(f"{sample_path}.{os.getpid()}.stacks",
                              "w") as fh:
                        for stack, n in counts.most_common(40):
                            fh.write(f"{n} {stack}\n")

        threading.Thread(target=_sampler, daemon=True,
                         name="sampler").start()
    try:
        code = asyncio.run(_amain())
    except KeyboardInterrupt:
        code = 0
    except BaseException as e:  # crashed main loop: leave evidence
        flight_recorder.flush_postmortem(f"{type(e).__name__}: {e}")
        raise
    for path, prof in _PROFILERS.items():
        try:
            prof.disable()
            prof.dump_stats(path)
        except Exception:
            pass
    # Skip interpreter teardown races from executor threads.
    os._exit(code or 0)


if __name__ == "__main__":
    main()
