"""Head-side cluster health plane: history store + alert engine glue.

Owned by the head service. Every metrics push that lands in the head
KV (``h_kv_put`` ns="metrics", plus the standalone head's own
``_report_node_metrics`` write) flows through
:meth:`ClusterHealthPlane.on_metrics_push`, which ingests the snapshot
into the bounded :class:`MetricsHistoryStore` and — at
``alerts_eval_interval_s`` cadence — sweeps the SLO rule engine. The
head's periodic pump also calls :meth:`tick` so alerts keep resolving
when pushes stop arriving (a dead cluster must not freeze its alerts
in the "firing" state forever).

Everything here is best-effort decoration on the KV write path: a
failure inside the plane must never fail a metrics push.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ray_tpu.core.config import Config


class ClusterHealthPlane:
    def __init__(self, config: Config,
                 session_dir: Optional[str] = None):
        from ray_tpu.util.alerts import AlertEngine, default_rules
        from ray_tpu.util.metrics_history import MetricsHistoryStore

        self.enabled = bool(config.metrics_history_enabled)
        self.store = MetricsHistoryStore(
            recent_points=config.metrics_history_recent_points,
            coarse_points=config.metrics_history_coarse_points,
            coarse_interval_s=config.metrics_history_coarse_interval_s,
            max_bytes=config.metrics_history_max_bytes,
            staleness_s=config.metrics_staleness_s,
            max_series_per_metric=(
                config.metrics_history_max_series_per_metric),
        )
        self.engine: Optional[AlertEngine] = None
        if self.enabled and config.alerts_enabled:
            self.engine = AlertEngine(self.store, rules=default_rules())
        self._eval_interval = float(config.alerts_eval_interval_s)
        self._last_eval = 0.0
        # Experiment-state journal: the metric trajectory and open-alert
        # state survive a head restart (the "what led here" record would
        # otherwise die with the process holding it).
        self._journal_dir: Optional[str] = None
        self._journal_interval = float(config.health_journal_interval_s)
        self._last_journal = 0.0
        if (self.enabled and session_dir
                and config.health_journal_enabled):
            self._journal_dir = os.path.join(session_dir,
                                             "health_journal")
            self._load_journal(config)

    # -- ingest (h_kv_put hook; must never raise) ------------------------

    def on_metrics_push(self, key, value,
                        now: Optional[float] = None) -> None:
        if not self.enabled:
            return
        try:
            proc = key.decode() if isinstance(key, (bytes, bytearray)) \
                else str(key)
            snap = json.loads(bytes(value).decode())
            if not isinstance(snap, dict):
                return
            now = time.time() if now is None else now
            self.store.ingest(proc, snap, ts=now)
            self.maybe_evaluate(now)
        except Exception as e:  # lint: allow-silent(health plane is decoration on the KV write path; see swallow below)
            from ray_tpu.util import flight_recorder

            flight_recorder.swallow("health.on_metrics_push", e)

    def on_proc_gone(self, key) -> None:
        if not self.enabled:
            return
        proc = key.decode() if isinstance(key, (bytes, bytearray)) \
            else str(key)
        self.store.on_proc_gone(proc)

    # -- evaluation ------------------------------------------------------

    def maybe_evaluate(self, now: Optional[float] = None) -> None:
        if self.engine is None:
            return
        now = time.time() if now is None else now
        if now - self._last_eval < self._eval_interval:
            return
        self._last_eval = now
        self.engine.evaluate(now)
        try:
            from ray_tpu.util import telemetry

            telemetry.set_gauge("ray_tpu_metrics_history_series",
                                self.store.series_count())
            telemetry.set_gauge("ray_tpu_metrics_history_bytes",
                                self.store.bytes_used)
        except Exception:  # lint: allow-silent(store stat gauges are decoration)
            pass

    def tick(self) -> None:
        """Pump-driven sweep so alerts resolve without fresh pushes."""
        self.maybe_evaluate()
        self.maybe_journal()

    # -- experiment-state journal ----------------------------------------

    def _load_journal(self, config: Config) -> None:
        """Reload the previous head's journal on start (best-effort:
        a corrupt or missing journal means starting cold, not failing
        head bring-up)."""
        try:
            hist_path = os.path.join(self._journal_dir, "history.json")
            if os.path.exists(hist_path):
                with open(hist_path) as f:
                    self.store.restore(json.load(f))
            if self.engine is not None:
                alerts_path = os.path.join(self._journal_dir,
                                           "alerts.json")
                if os.path.exists(alerts_path):
                    with open(alerts_path) as f:
                        self.engine.restore(json.load(f))
                    # Restored firing alerts must not be insta-resolved
                    # by the first sweep before any process has pushed
                    # again: hold evaluation for one staleness window.
                    self._last_eval = (time.time()
                                       + float(config.metrics_staleness_s))
        except Exception as e:  # lint: allow-silent(journal reload is decoration on head start; see swallow)
            from ray_tpu.util import flight_recorder

            flight_recorder.swallow("health.load_journal", e)

    def maybe_journal(self, now: Optional[float] = None) -> None:
        """Write the history rings + open-alert state to the session
        dir at ``health_journal_interval_s`` cadence (tmp + rename, so
        a crash mid-write leaves the previous journal intact)."""
        if self._journal_dir is None:
            return
        now = time.time() if now is None else now
        if now - self._last_journal < self._journal_interval:
            return
        self._last_journal = now
        try:
            os.makedirs(self._journal_dir, exist_ok=True)
            docs = [("history.json", self.store.snapshot(512))]
            if self.engine is not None:
                docs.append(("alerts.json",
                             self.engine.journal_state()))
            for name, doc in docs:
                path = os.path.join(self._journal_dir, name)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=str)
                os.replace(tmp, path)
        except Exception as e:  # lint: allow-silent(journal write is decoration on the pump; see swallow)
            from ray_tpu.util import flight_recorder

            flight_recorder.swallow("health.journal", e)

    # -- handler payloads ------------------------------------------------

    def history_reply(self, payload: dict) -> dict:
        if not self.enabled:
            return {"enabled": False, "series": []}
        name = payload.get("name")
        if not name:
            return {"enabled": True, "series": self.store.index(),
                    "bytes": self.store.bytes_used,
                    "evictions": self.store.evictions}
        window_s = float(payload.get("window_s") or 600.0)
        tags = payload.get("tags") or None
        out = {
            "enabled": True, "name": name, "window_s": window_s,
            "series": self.store.query_points(
                name, window_s=window_s, tags=tags,
                max_points=int(payload.get("max_points") or 360)),
        }
        agg = payload.get("agg")
        if agg:
            out["agg"] = agg
            out["aggregates"] = self.store.window_agg(
                name, agg, window_s, tags=tags)
        return out

    def snapshot_reply(self, payload: dict) -> dict:
        if not self.enabled:
            return {"enabled": False, "series": [], "series_count": 0}
        snap = self.store.snapshot(
            max_points=int(payload.get("max_points") or 512))
        snap["enabled"] = True
        return snap

    def alerts_reply(self) -> dict:
        if self.engine is None:
            return {"enabled": False, "firing": [], "episodes": [],
                    "rules": []}
        # Sweep before answering so the caller never sees an alert that
        # already aged out but hasn't been re-evaluated.
        self.engine.evaluate()
        return self.engine.state()

    def put_rule(self, payload: dict) -> dict:
        from ray_tpu.util.alerts import AlertRule

        if self.engine is None:
            return {"ok": False, "error": "alert engine disabled"}
        try:
            if payload.get("remove"):
                self.engine.remove_rule(str(payload["remove"]))
                return {"ok": True, "rules": len(self.engine.rules)}
            rule = AlertRule.from_dict(payload)
            self.engine.add_rule(rule)
            return {"ok": True, "rules": len(self.engine.rules)}
        except Exception as e:
            return {"ok": False, "error": str(e)}
