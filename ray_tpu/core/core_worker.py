"""CoreWorker — the per-process runtime embedded in every driver and worker.

Reference: src/ray/core_worker/core_worker.h:290. Responsibilities:

- task submission with lease-based scheduling (reference:
  transport/direct_task_transport.h:75 — queue per SchedulingKey, lease a
  worker from the head, pipeline pushes onto leased workers, return the
  lease after an idle timeout)
- actor task submission with per-actor ordered queues and state machine
  (reference: transport/direct_actor_task_submitter.h:74)
- ownership: every created object is owned by this worker; the in-process
  memory store serves small objects to borrowers; large objects live in the
  node's shared-memory store (reference: reference_count.h, memory_store.h)
- task manager with retries and error-object fallout (reference:
  task_manager.h)
- get/put/wait and the object-resolution protocol.

The public API module (`ray_tpu/api.py`) is a thin veneer over this class.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu.core import object_store, object_transfer, retry, rpc, serialization
from ray_tpu.core.config import Config
from ray_tpu.core.ids import (
    ActorID,
    IndexCounter,
    JobID,
    ObjectID,
    TaskID,
    WorkerID,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import MemoryStore, ShmStore
from ray_tpu.core.serialization import SerializedObject
from ray_tpu.core.task_spec import Address, TaskArg, TaskSpec, TaskType

logger = logging.getLogger(__name__)

IN_PLASMA = b"P"  # metadata marker: value lives in the shm store


def _swallow(site: str, error: BaseException, **tags) -> None:
    """Evidence for intentionally-dropped errors (silent-except audit):
    the handler stays non-fatal, but the drop rides the flight recorder
    (guard/swallowed) so ``debug dump`` can explain it later. Lazy
    import: util package init must not run during core import."""
    from ray_tpu.util import flight_recorder

    flight_recorder.swallow(site, error, **tags)


def make_plasma_marker() -> SerializedObject:
    return SerializedObject(metadata=IN_PLASMA, inband=b"", buffers=[])


class HeadClient:
    """Async client to the head service; remote (socket) or local."""

    def __init__(self, conn: Optional[rpc.Connection] = None,
                 local_service=None, local_peer=None):
        self._conn = conn
        self._local = local_service
        self._local_peer = local_peer
        if (conn is None) == (local_service is None):
            raise ValueError("exactly one of conn/local_service required")
        if local_service is not None:
            self._handlers = local_service.handlers()

    async def call(self, method: str, payload=None, timeout=None):
        if self._conn is not None:
            return await self._conn.call(method, payload, timeout=timeout)
        # Local (in-process driver) path: these calls never cross a
        # socket, so Connection._dispatch can't account them — record
        # into the same process-global table here or the busiest caller
        # of an embedded head would be invisible to the observatory.
        handler = self._handlers[method]
        from ray_tpu.util import telemetry

        if not telemetry.enabled():
            if timeout is not None:
                return await asyncio.wait_for(
                    handler(self._local_peer, payload), timeout
                )
            return await handler(self._local_peer, payload)
        from ray_tpu.util import rpc_stats

        t0 = time.perf_counter()
        ok = True
        try:
            if timeout is not None:
                return await asyncio.wait_for(
                    handler(self._local_peer, payload), timeout
                )
            return await handler(self._local_peer, payload)
        except Exception:
            ok = False
            raise
        finally:
            rpc_stats.server_stats().record(
                method, rpc_stats.caller_kind(self._local_peer),
                0.0, time.perf_counter() - t0, ok=ok)

    @property
    def closed(self):
        return self._conn.closed if self._conn is not None else False


class ReferenceCounter:
    """Tracks local and borrowed references (reference: reference_count.h).

    Owned objects are freed when local refs and known borrows reach zero.
    Borrowed refs notify the owner on destruction. Borrow accounting is
    conservative: a ref serialized into a task's args counts as a borrow
    until the consumer's interpreter drops it.
    """

    def __init__(self, core_worker: "CoreWorker"):
        self.cw = core_worker
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock("core_worker.ReferenceCounter._lock")
        # object hex -> {"local": n, "borrows": n, "owned": bool, "shm": bool}
        self._refs: Dict[str, dict] = {}
        self._disabled = False
        # GC-deferred removals: ObjectRef.__del__ runs from the garbage
        # collector, which can fire at ANY allocation site — including
        # inside our own critical sections (observed: register_owned
        # held _lock, an allocation triggered GC, a dead ref's __del__
        # re-entered remove_local_ref → self-deadlock). Finalizers
        # therefore only append here (deque.append is atomic and safe
        # in GC context); every other entry point drains first.
        self._deferred: deque = deque()

    def disable(self):
        self._disabled = True

    def _entry(self, hex_id: str) -> dict:
        return self._refs.setdefault(
            hex_id, {"local": 0, "borrows": 0, "owned": False,
                     "shm": False, "device": False}
        )

    def _drain_deferred(self):
        if self._disabled:
            self._deferred.clear()  # teardown: stores are going away
            return
        while True:
            try:
                hex_id, object_id, owner = self._deferred.popleft()
            except IndexError:
                return
            self._remove_local_ref_now(hex_id, object_id, owner)

    def register_owned(self, object_id: ObjectID, in_shm: bool,
                       device: bool = False):
        if self._disabled:
            return
        self._drain_deferred()
        with self._lock:
            entry = self._entry(object_id.hex())
            entry["owned"] = True
            entry["shm"] = in_shm
            entry["device"] = device

    def add_local_ref(self, ref: ObjectRef):
        if self._disabled:
            return
        self._drain_deferred()
        with self._lock:
            self._entry(ref.hex())["local"] += 1

    def remove_local_ref(self, ref: ObjectRef):
        """Called from ObjectRef.__del__ — GC context. MUST NOT take
        _lock (see __init__); the removal is queued and applied at the
        next refcounter entry point."""
        if self._disabled:
            return
        self._deferred.append((ref.hex(), ref.id, ref.owner_address))

    def _remove_local_ref_now(self, hex_id: str, object_id: ObjectID,
                              owner) -> None:
        to_free = None
        notify_owner = None
        with self._lock:
            entry = self._refs.get(hex_id)
            if entry is None:
                return
            entry["local"] -= 1
            if entry["local"] <= 0 and entry["borrows"] <= 0:
                if entry["owned"]:
                    to_free = (object_id, entry["shm"],
                               entry.get("device", False))
                elif owner is not None:
                    notify_owner = owner
                self._refs.pop(hex_id, None)
        if to_free is not None:
            self.cw._free_owned_object(to_free[0], to_free[1],
                                       device=to_free[2])
        elif notify_owner is not None:
            self.cw._release_borrowed_device_copy(object_id)
            self.cw._notify_owner_ref_removed(object_id, notify_owner)

    def on_ref_serialized(self, ref: ObjectRef):
        """The serializer registers the borrow (+1 on the owner); the
        eventual consumer's ref destruction sends the matching -1
        (remove_ref). This keeps increments and decrements one-to-one."""
        if self._disabled:
            return
        self._drain_deferred()
        notify_owner = None
        with self._lock:
            entry = self._refs.get(ref.hex())
            if entry is not None and entry["owned"]:
                entry["borrows"] += 1
            elif ref.owner_address is not None:
                notify_owner = ref.owner_address
        if notify_owner is not None:
            self.cw._notify_owner_add_borrow(ref.id, notify_owner)

    def on_ref_deserialized(self, ref: ObjectRef):
        # Borrow already counted by the serializer; nothing to do beyond
        # the local-ref tracking done in ObjectRef.__init__.
        pass

    def on_borrow_added(self, object_id: ObjectID):
        self._drain_deferred()
        with self._lock:
            self._entry(object_id.hex())["borrows"] += 1

    def on_borrow_removed(self, object_id: ObjectID):
        self._drain_deferred()
        to_free = None
        with self._lock:
            entry = self._refs.get(object_id.hex())
            if entry is None:
                return
            entry["borrows"] -= 1
            if entry["local"] <= 0 and entry["borrows"] <= 0 and entry["owned"]:
                to_free = (object_id, entry["shm"],
                           entry.get("device", False))
                self._refs.pop(object_id.hex(), None)
        if to_free is not None:
            self.cw._free_owned_object(to_free[0], to_free[1],
                                       device=to_free[2])

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)


@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    pushed_to: Optional[WorkerID] = None
    cancelled: bool = False
    # True once the executing worker acked the push (sent before user
    # code runs). A worker failure with accepted=False means the task
    # never started, so its retry is free — a push written into a
    # dead worker's socket must not drain the retry budget.
    accepted: bool = False
    # Safety cap on free retries (a worker that reliably dies between
    # push and ack would otherwise loop forever).
    free_retries: int = 10


class ObjectRefGenerator:
    """Iterator over a streaming task's return refs (reference:
    task_manager.h:98 ObjectRefStream / TryReadObjectRefStream). Items
    become ObjectRefs as the executing worker reports them; iteration
    blocks until the next item or end-of-stream. Also asynchronously
    iterable (``async for ref in gen``) — waiters are woken through
    one-shot callbacks instead of blocking a pool thread per stream."""

    def __init__(self, task_id: TaskID, cleanup=None):
        self._task_id = task_id
        self._items: List[ObjectRef] = []
        self._read = 0
        self._total: Optional[int] = None  # known once the task finishes
        self._error: Optional[Exception] = None
        self._cv = threading.Condition()
        # Deregisters this stream from the owner once fully consumed;
        # the registration must outlive the final task reply because
        # item notifications can still be in flight behind it.
        self._cleanup = cleanup or (lambda: None)
        # One-shot wakeups for async iterators (invoked on append/finish
        # from whatever thread produced the event; the registrar wraps
        # them in call_soon_threadsafe).
        self._wakeups: List[Any] = []
        # Consumption hook (backpressure acks): called with the running
        # read count each time the consumer takes an item. Set by the
        # CoreWorker when the producer requested flow control.
        self._on_read = None
        # Lifecycle observers: fired exactly once with a terminal tag —
        # "ok" (finished cleanly), "error" (finished with an error), or
        # "released" (consumer dropped the stream early).
        self._done_cbs: List[Any] = []
        self._first_item_cbs: List[Any] = []
        self._terminal: Optional[str] = None
        # Set by close(): iteration ends immediately, including for
        # consumers blocked in __next__/__anext__ on OTHER threads (the
        # gRPC cancel callback closes from a different thread than the
        # handler iterating the stream).
        self._released = False

    # -- producer side (CoreWorker) ------------------------------------
    def _drain_wakeups_locked(self):
        wakeups, self._wakeups = self._wakeups, []
        return wakeups

    def _append(self, ref: ObjectRef) -> bool:
        """Returns False when the consumer already released the stream
        (close() raced this chunk's delivery) — the caller must not
        treat the chunk as delivered; dropping its ref reclaims it
        through the normal owned-object GC path."""
        with self._cv:
            if self._released:
                return False
            first = not self._items
            self._items.append(ref)
            self._cv.notify_all()
            wakeups = self._drain_wakeups_locked()
            first_cbs = list(self._first_item_cbs) if first else []
            self._first_item_cbs = []
        for cb in first_cbs:
            _call_quietly(cb)
        for cb in wakeups:
            _call_quietly(cb)
        return True

    def _finish(self, total: int, error: Optional[Exception] = None):
        with self._cv:
            self._total = total
            self._error = error
            self._cv.notify_all()
            wakeups = self._drain_wakeups_locked()
        for cb in wakeups:
            _call_quietly(cb)
        self._fire_terminal("error" if error is not None else "ok")

    def _fire_terminal(self, tag: str):
        with self._cv:
            if self._terminal is not None:
                return
            self._terminal = tag
            cbs, self._done_cbs = self._done_cbs, []
        for cb in cbs:
            _call_quietly(cb, tag, self)

    # -- observers ------------------------------------------------------
    def add_done_callback(self, cb):
        """``cb(tag, gen)`` fires exactly once when the stream reaches
        a terminal state: "ok" / "error" (producer finished) or
        "released" (consumer abandoned it first). The generator is
        passed as an argument so observers need not capture it —
        a closure over the gen stored in its own callback list would be
        a reference cycle keeping abandoned streams alive until the
        cyclic GC."""
        with self._cv:
            if self._terminal is None:
                self._done_cbs.append(cb)
                return
            tag = self._terminal
        _call_quietly(cb, tag, self)

    def add_first_item_callback(self, cb):
        """``cb()`` fires when the first chunk lands (TTFT hooks)."""
        with self._cv:
            if not self._items:
                self._first_item_cbs.append(cb)
                return
        _call_quietly(cb)

    def error(self) -> Optional[Exception]:
        with self._cv:
            return self._error

    def items_produced(self) -> int:
        with self._cv:
            return len(self._items)

    # -- consumer side --------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._next_internal(timeout=None)

    def next_ready(self, timeout: Optional[float] = None) -> ObjectRef:
        return self._next_internal(timeout=timeout)

    def _take_locked(self) -> Optional[ObjectRef]:
        """(cv held) Pop the next ready item, or None. Raises at
        end-of-stream."""
        if self._released:
            raise StopIteration
        if self._read < len(self._items):
            ref = self._items[self._read]
            self._read += 1
            return ref
        if self._total is not None and self._read >= self._total:
            self._cleanup()
            if self._error is not None:
                raise self._error
            raise StopIteration
        return None

    def _took(self):
        """Post-take consumption hook (ack the producer) — called
        OUTSIDE the cv so a slow ack can't stall producers appending."""
        if self._on_read is not None:
            _call_quietly(self._on_read, self._read)

    def _next_internal(self, timeout: Optional[float]) -> ObjectRef:
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with self._cv:
            while True:
                ref = self._take_locked()
                if ref is not None:
                    break
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise exc.GetTimeoutError(
                            "stream item not ready in time")
                self._cv.wait(timeout=remaining)
        self._took()
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        loop = asyncio.get_running_loop()
        while True:
            with self._cv:
                try:
                    ref = self._take_locked()
                except StopIteration:
                    raise StopAsyncIteration
                if ref is None:
                    event = asyncio.Event()
                    self._wakeups.append(
                        lambda: loop.call_soon_threadsafe(event.set))
            if ref is not None:
                self._took()
                return ref
            await event.wait()

    def close(self):
        """Abandon the stream: release owner-side state and cancel the
        producer if it is still yielding. Consumers blocked in
        __next__/__anext__ (possibly on other threads) are woken and
        see end-of-stream."""
        with self._cv:
            self._released = True
            self._cv.notify_all()
            wakeups = self._drain_wakeups_locked()
        for cb in wakeups:
            _call_quietly(cb)
        self._fire_terminal("released")
        try:
            self._cleanup()
        except Exception as e:
            _swallow("generator.close.cleanup", e)

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: allow-silent(__del__ during interpreter teardown must not raise)
            pass

    def completed(self) -> bool:
        with self._cv:
            return self._total is not None and self._read >= self._total


def _call_quietly(cb, *args):
    try:
        cb(*args)
    except Exception:
        logger.debug("stream callback failed", exc_info=True)


@dataclass
class LeasedWorker:
    worker_id: WorkerID
    address: Tuple[str, int]
    lease_id: str
    conn: rpc.Connection
    busy: int = 0  # in-flight pushed tasks
    idle_since: float = 0.0


@dataclass
class SchedulingKeyState:
    queue: deque = field(default_factory=deque)  # of TaskSpec
    workers: Dict[WorkerID, LeasedWorker] = field(default_factory=dict)
    inflight_lease_requests: int = 0


@dataclass
class ActorState:
    actor_id: ActorID
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    address: Optional[Address] = None
    conn: Optional[rpc.Connection] = None
    queue: deque = field(default_factory=deque)  # buffered specs pre-ALIVE
    seqno: int = 0
    inflight: int = 0
    death_cause: str = ""
    max_task_retries: int = 0
    poller: Optional[asyncio.Task] = None  # reconciliation loop (see below)
    # Same-tick submissions coalesce into one batched push frame.
    push_buf: List["TaskSpec"] = field(default_factory=list)
    push_flush_scheduled: bool = False


class CoreWorker:
    def __init__(self, config: Config, loop_thread: rpc.EventLoopThread,
                 head: HeadClient, job_id: JobID, worker_id: WorkerID,
                 mode: str, host: str = "127.0.0.1",
                 advertise_host: Optional[str] = None):
        self.config = config
        self.loop_thread = loop_thread
        self.loop = loop_thread.loop
        self.head = head
        self.job_id = job_id
        self.worker_id = worker_id
        self.mode = mode  # "driver" | "worker"
        self.host = host  # bind address
        # Address peers should dial (refs carry it as the owner address);
        # differs from the bind host when binding 0.0.0.0 on remote hosts.
        self.advertise_host = advertise_host or (
            host if host != "0.0.0.0" else "127.0.0.1")
        self.port: Optional[int] = None
        self.address: Optional[Address] = None

        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter(self)
        self._task_counter = IndexCounter()
        self._put_counter = IndexCounter()
        # The "current task" driving put/return ids. For drivers this is a
        # synthetic root task per process.
        self._root_task_id = TaskID.for_normal_task(job_id)
        self._current_task_id = threading.local()

        self.pending_tasks: Dict[TaskID, PendingTask] = {}
        self.scheduling_keys: Dict[tuple, SchedulingKeyState] = {}
        self.actors: Dict[ActorID, ActorState] = {}
        self._conn_cache: Dict[Tuple[str, int], rpc.Connection] = {}
        self._conn_cache_lock = asyncio.Lock()
        self._function_cache: Dict[str, Any] = {}
        self._exported_functions: Dict[int, str] = {}
        self._actor_sub_started = False
        self._shutdown = False
        # Bumped whenever an ordered owner-bound notification is queued
        # (see _notify_owner_add_borrow); read by the worker executor's
        # sync-reply fast path.
        self.owner_notify_epoch = 0
        self.server: Optional[rpc.Server] = None
        self._finished_task_ids: set = set()
        self._pubsub_callbacks: Dict[str, List[Callable]] = {}
        self._loop_thread_ident: Optional[int] = None
        # Task-event buffer: appended from executor threads AND the loop
        # thread; all access goes through the lock.
        self._task_event_buf: List[dict] = []
        from ray_tpu.util.locks import make_lock

        self._task_event_lock = make_lock(
            "core_worker.CoreWorker._task_event_lock")
        self._event_flush_scheduled = False
        # Streaming-generator tasks: task id -> ObjectRefGenerator.
        # WEAK values: the registry must not keep an abandoned stream
        # alive, or the consumer dropping its generator (the documented
        # cancel-by-abandonment path, __del__ -> close) could never
        # fire and the producer would stream into the void forever.
        self._streams: "weakref.WeakValueDictionary[TaskID, ObjectRefGenerator]" = (
            weakref.WeakValueDictionary())
        # Pushed-but-unreplied tasks: task_id hex -> ("task", spec, lw,
        # key, state, conn) | ("actor", spec, actor_state, conn). Results
        # stream back as task_done notifications (h_task_done); a
        # connection close fails exactly the entries for that conn.
        self._outstanding_pushes: Dict[str, tuple] = {}
        # This process's node (for object-directory reports); workers get
        # it from the spawn env, the driver from the head's default node.
        node_hex = os.environ.get("RAY_TPU_NODE_ID")
        self.node_id_hex: Optional[str] = node_hex
        # Cross-node pull manager (lazy: only touched on a local miss).
        self._puller = object_transfer.ObjectPuller(self.get_connection)
        # Lineage: creating-task specs of owned plasma objects, retained
        # under a byte budget so a lost object can be reconstructed by
        # resubmitting its task (object_recovery_manager.h:41, budget:
        # task_manager.h:202). Ordered for FIFO eviction.
        self._lineage: "OrderedDict[ObjectID, tuple]" = OrderedDict()
        self._lineage_bytes = 0
        # task_id -> in-flight recovery future (coalesces racing gets).
        self._recovering: Dict[TaskID, asyncio.Future] = {}
        # Unified retry envelope for this process's RPC stack (task and
        # actor pushes, control-plane polls, recovery probes). Shared so
        # retry counts are observable in one place.
        self._rpc_retry = retry.RetryPolicy.from_config(config)
        # Slower envelope for state-convergence probes (object-directory
        # re-checks, death-reason queries): the signal travels through
        # third parties, so sub-100ms retries just burn RPCs.
        self._probe_retry = retry.RetryPolicy.from_config(
            config, base_delay_s=0.4, multiplier=2.5, max_delay_s=1.0,
            jitter=0.0)
        # Burst-coalesced submission queue (API thread -> loop).
        self._submit_buf: List[TaskSpec] = []
        self._submit_lock = make_lock(
            "core_worker.CoreWorker._submit_lock")
        self._submit_wake_pending = False
        try:
            self.loop.call_soon_threadsafe(
                lambda: setattr(self, "_loop_thread_ident",
                                threading.get_ident())
            )
        except Exception as e:
            _swallow("init.loop_ident_probe", e)
        # Set by worker_main for executor duties.
        self.executor = None

    # ------------------------------------------------------------------
    # bootstrap
    # ------------------------------------------------------------------

    def handlers(self) -> dict:
        return {
            "get_object": self.h_get_object,
            "add_borrow": self.h_add_borrow,
            "remove_ref": self.h_remove_ref,
            "pubsub": self.h_pubsub,
            "stream_item": self.h_stream_item,
            "task_accepted": self.h_task_accepted,
            "task_done": self.h_task_done,
            "ping": self.h_ping,
            "debug_dump": self.h_debug_dump,
            "profile_capture": self.h_profile_capture,
            "device_trace_capture": self.h_device_trace_capture,
            "fetch_device_shard": self.h_fetch_device_shard,
            "donate_device_shards": self.h_donate_device_shards,
        }

    async def h_debug_dump(self, conn, payload):
        """On-demand debug plane (reference: `ray stack` / the reporter
        agent's py-spy hooks): this process's flight-recorder ring plus
        live stacks of every thread. The head fans this out cluster-wide
        (h_debug_dump_cluster)."""
        payload = payload or {}
        from ray_tpu.util import flight_recorder

        out = {
            "pid": os.getpid(),
            "worker_id": self.worker_id.hex(),
            "mode": self.mode,
            "node_id": self.node_id_hex,
            "ts": time.time(),
            "stacks": (flight_recorder.dump_stacks()
                       if payload.get("include_stacks", True) else {}),
        }
        if payload.get("include_events", True):
            out["events"] = flight_recorder.snapshot(
                limit=payload.get("event_limit"))
        return out

    async def h_profile_capture(self, conn, payload):
        """Live profiling plane (reference: the reporter agent's py-spy
        capture): sample this process's threads for a bounded window and
        return folded stacks with task attribution. The sampling loop
        blocks, so it runs on the executor pool — the event loop keeps
        serving (heartbeats, acks, the task being profiled)."""
        payload = payload or {}
        from ray_tpu.util import profiler

        duration = float(payload.get("duration_s", 5.0))
        hz = float(payload.get("hz", 100.0))
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: profiler.capture(duration, hz))
        out.update(worker_id=self.worker_id.hex(), mode=self.mode,
                   node_id=self.node_id_hex)
        return out

    async def h_device_trace_capture(self, conn, payload):
        """Device-trace plane: run a bounded jax.profiler window in
        this process and return the parsed ops/steps/lanes plus raw
        trace bytes. start/stop_trace and the parse both block, so the
        whole capture runs on the executor pool — the event loop keeps
        serving while the (likely jitted-step) workload is traced. A
        capture already in flight is rejected inside capture() with a
        structured error, never queued."""
        payload = payload or {}
        from ray_tpu.util import device_trace

        duration = float(payload.get("duration_s", 2.0))
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: device_trace.capture(duration))
        out.update(worker_id=self.worker_id.hex(), mode=self.mode,
                   node_id=self.node_id_hex)
        return out

    def h_task_accepted(self, conn, payload):
        # Sync notification handler (rpc fast path: no Task per frame).
        pending = self.pending_tasks.get(
            TaskID.from_hex(payload["task_id"]))
        if pending is not None:
            pending.accepted = True

    def _ingest_return(self, ret: dict) -> ObjectID:
        """Record one task-return payload (inline value or plasma
        marker) into the local store with ownership."""
        object_id = ObjectID(ret["object_id"])
        if ret.get("in_plasma"):
            self.memory_store.put(object_id, make_plasma_marker())
            self.reference_counter.register_owned(object_id, True)
        else:
            obj = SerializedObject(
                metadata=ret["metadata"], inband=ret["inband"],
                buffers=list(ret.get("buffers", [])),
            )
            self.memory_store.put(object_id, obj)
            self.reference_counter.register_owned(object_id, False)
        return object_id

    def _release_stream(self, task_id: TaskID):
        """Consumer dropped or exhausted the generator: deregister, and
        cancel the producer if it is still running so an abandoned
        stream doesn't keep yielding. Normal tasks go through the lease
        plane's cancel; actor-lane streams notify the actor's executor
        directly over its connection.

        NB: gate on pending-task state, NOT on the registry entry — the
        weak _streams entry is already gone when this runs from the
        generator's own __del__."""
        self._streams.pop(task_id, None)
        pending = self.pending_tasks.get(task_id)
        if pending is None or pending.cancelled:
            return
        spec = pending.spec
        if spec.task_type == TaskType.ACTOR_TASK:
            def go():
                pending.cancelled = True
                state = self.actors.get(spec.actor_id)
                if (state is not None and state.conn is not None
                        and not state.conn.closed):
                    state.conn.notify_forget(
                        "cancel_task",
                        {"task_id": spec.task_id.hex(), "force": False})

            try:
                self.loop.call_soon_threadsafe(go)
            except Exception as e:
                _swallow("stream.release.cancel_notify", e,
                         task=task_id.hex()[:16])
            return
        try:
            ref = ObjectRef(ObjectID.for_task_return(task_id, 1),
                            self.address, is_owned=False)
            self.cancel_task(ref, force=False)
        except Exception as e:
            _swallow("stream.release.cancel_task", e,
                     task=task_id.hex()[:16])

    def h_stream_item(self, conn, payload):
        """A streaming task's executor reports one yielded item
        (reference: the streaming-generator return path feeding
        ObjectRefStream). SYNC notification handler deliberately: the
        final task_done reply is dispatched inline, so item frames must
        be too — an async handler's queued task would let the finish
        overtake in-flight items and fire stream-terminal accounting
        before the last chunks land."""
        task_id = TaskID.from_hex(payload["task_id"])
        gen = self._streams.get(task_id)
        if gen is None:
            # Abandoned stream: the consumer is gone, so this item has
            # no owner. Free the sealed copy instead of leaking a
            # pinned arena object.
            if payload.get("in_plasma"):
                object_id = ObjectID(payload["object_id"])
                asyncio.ensure_future(self.head.call(
                    "free_objects", {"object_ids": [object_id.hex()]}))
            return {"ok": False}
        if payload.get("ack") and gen._on_read is None:
            # The producer is flow-controlled: ack every consumed item
            # with the running read count so its credit window reopens.
            # Rides the item connection back; loop-thread send.
            task_hex = payload["task_id"]

            def ack(read, conn=conn, task_hex=task_hex):
                def send():
                    if not conn.closed:
                        conn.notify_forget(
                            "stream_ack",
                            {"task_id": task_hex, "read": read})

                self.loop.call_soon_threadsafe(send)

            gen._on_read = ack
        object_id = self._ingest_return(payload)
        ref = ObjectRef(object_id, self.address, is_owned=True)
        if not gen._append(ref):
            # close() raced this chunk between the registry lookup and
            # the append: ownership IS registered, so simply dropping
            # the ref reclaims the value (including a sealed shm copy)
            # through the owned-object GC path.
            del ref
            return {"ok": False}
        return {"ok": True}

    async def start_server(self, extra_handlers: Optional[dict] = None) -> int:
        handlers = self.handlers()
        if extra_handlers:
            handlers.update(extra_handlers)
        self.server = rpc.Server(handlers, name=f"cw-{self.worker_id.hex()[:8]}")
        self.port = await self.server.start(self.host, 0)
        self.address = Address(self.advertise_host, self.port,
                               self.worker_id.hex())

        async def ref_gc_loop():
            # Guaranteed drain for GC-deferred ref removals: without it,
            # a process that stops touching the reference counter would
            # postpone frees/remove_ref notifications indefinitely.
            while not self._shutdown:
                await asyncio.sleep(1.0)
                try:
                    self.reference_counter._drain_deferred()
                except Exception:
                    logger.exception("deferred ref drain failed")

        asyncio.get_running_loop().create_task(ref_gc_loop())
        return self.port

    def current_task_id(self) -> TaskID:
        return getattr(self._current_task_id, "value", self._root_task_id)

    def set_current_task_id(self, task_id: Optional[TaskID]):
        self._current_task_id.value = task_id or self._root_task_id

    # ------------------------------------------------------------------
    # connections
    # ------------------------------------------------------------------

    async def get_connection(self, address: Tuple[str, int]) -> rpc.Connection:
        conn = self._conn_cache.get(address)
        if conn is not None and not conn.closed:
            return conn
        async with self._conn_cache_lock:
            conn = self._conn_cache.get(address)
            if conn is not None and not conn.closed:
                return conn
            conn = await rpc.connect(
                address[0], address[1], self.handlers(),
                name=f"peer-{address[1]}",
                timeout=self.config.rpc_connect_timeout_s,
            )
            # Streamed task replies (task_done) need a close hook: when
            # the peer dies, every outstanding push on it must fail NOW
            # rather than hang awaiting a notification that won't come.
            prev_close = conn.on_close

            def on_close(c, _prev=prev_close):
                if _prev:
                    _prev(c)
                self._fail_worker_conn(
                    c, rpc.ConnectionLost(f"peer-{address[1]}"))

            conn.on_close = on_close
            self._conn_cache[address] = conn
            return conn

    # ------------------------------------------------------------------
    # put / get / wait / free
    # ------------------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        object_id = ObjectID.for_put(self.current_task_id(),
                                     self._put_counter.next())
        obj = self._serialize_for_put(object_id, value)
        self.put_serialized(object_id, obj)
        return ObjectRef(object_id, self.address, is_owned=True)

    def _serialize_for_put(self, object_id: ObjectID,
                           value: Any) -> SerializedObject:
        """Serialize a put value, routing qualifying jax.Array leaves
        through the device plane (per-shard device buffers + a tiny
        placeholder envelope) instead of the host-numpy bounce."""
        from ray_tpu.core import device_objects

        if not device_objects.plane_enabled(self.config):
            return serialization.serialize(value)
        exported: dict = {}

        def exporter(v):
            try:
                mapped, count, descs = device_objects.export_value(
                    object_id, v, self.config)
            except Exception as e:
                _swallow("device_objects.export", e,
                         object=object_id.hex()[:16])
                return v, 0
            if count:
                exported["descs"] = descs
            return mapped, count

        try:
            obj = serialization.serialize(value, device_exporter=exporter)
        except BaseException:
            # Serialization of the non-device remainder failed after the
            # export already registered shards: don't leak the entry.
            if exported:
                device_objects.drop(object_id.hex())
            raise
        if obj.metadata == serialization.DEVICE:
            self._register_device_manifest(object_id, obj,
                                           exported["descs"])
        return obj

    def _register_device_manifest(self, object_id: ObjectID,
                                  obj: SerializedObject,
                                  descs: List[dict]) -> None:
        """Record the sharding manifest in the head's owner table (next
        to the location entry) and start serving shards. Small envelopes
        are mirrored so holders can serve the object after this owner
        dies (replica cold-start-from-peer)."""
        from ray_tpu.core import device_objects

        total_bytes = sum(int(d.get("nbytes", 0)) for d in descs)
        envelope = None
        if obj.total_size() <= device_objects.MANIFEST_ENVELOPE_CAP:
            envelope = [obj.metadata, obj.inband,
                        [bytes(memoryview(b)) for b in obj.buffers]]
        fut = self.loop_thread.submit(
            self.head.call("device_object_put", {
                "object_id": object_id.hex(),
                "manifest": descs,
                "holder": list(self._device_holder_address()),
                "envelope": envelope,
                "total_bytes": total_bytes,
            }))

        def _observe(f, hex_id=object_id.hex()):
            # A lost registration makes the put silently unfetchable
            # cross-process ("no registered holders") — leave evidence
            # tying that symptom to its cause.
            err = f.exception()
            if err is not None:
                _swallow("device.manifest_register", err,
                         object=hex_id[:16])

        fut.add_done_callback(_observe)

    def _device_holder_address(self) -> Tuple[str, int, int]:
        """(host, worker rpc port, data-plane port) other processes use
        to pull shards from this one."""
        data_port = object_transfer.ensure_data_server()
        return (self.address.host, self.address.port, data_port)

    def put_serialized(self, object_id: ObjectID, obj: SerializedObject):
        device = obj.metadata == serialization.DEVICE
        in_shm = (obj.total_size() > self.config.max_direct_call_object_size
                  and not getattr(self, "no_node_store", False))
        if in_shm:
            size = self._seal_to_shm(object_id, obj)
            self.memory_store.put(object_id, make_plasma_marker())
            self.loop_thread.submit(
                self.head.call("object_sealed",
                               {"object_id": object_id.hex(), "size": size,
                                "node_id": self.node_id_hex})
            )
        else:
            self.memory_store.put(object_id, obj)
        self.reference_counter.register_owned(object_id, in_shm,
                                              device=device)

    def _seal_to_shm(self, object_id: ObjectID, obj: SerializedObject) -> int:
        size = object_store.node_store_write(object_id, obj)
        from ray_tpu.util import flight_recorder

        # Only shm-plane objects are recorded: tiny in-process values
        # churn far too fast for a forensic ring.
        flight_recorder.record("object", "sealed",
                               object=object_id.hex()[:16], bytes=size,
                               node=self.node_id_hex or "head")
        return size

    def _check_not_on_loop(self, api: str):
        if threading.get_ident() == getattr(self, "_loop_thread_ident", None):
            raise RuntimeError(
                f"{api} would block the event loop (called from an async "
                f"actor method?). Use `await ref` / the async API instead."
            )

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None,
            donate: bool = False) -> List[Any]:
        self._check_not_on_loop("get()")
        fut = self.loop_thread.submit(
            self._get_all_async(refs, timeout, donate=donate))
        return fut.result()

    async def _get_all_async(self, refs: List[ObjectRef],
                             timeout: Optional[float],
                             donate: bool = False) -> List[Any]:
        """Batched get with a single awaitable for every owned-local
        pending ref: per-ref ``gather`` + ``wait_for`` costs an asyncio
        Task and a timer handle per object — at tiny-object rates that
        machinery dominates the driver's ingest path. Remote-owner
        fetches (cross-process borrows) keep the per-ref coroutine
        path; they already pay an RPC each."""
        objs: List[Optional[SerializedObject]] = [
            self.memory_store.get_if_exists(ref.id) for ref in refs]
        pending_local: List[int] = []
        remote: List[int] = []
        for i, (ref, obj) in enumerate(zip(refs, objs)):
            if obj is not None:
                continue
            # Ownership is by ADDRESS first: a ref whose owner is
            # another process must be fetched from it even when the
            # task-id heuristic matches one of ours (see
            # _resolve_object).
            owner = ref.owner_address
            owner_is_self = (owner is None
                             or owner.key() == self.address.key())
            if owner_is_self and self._owns(ref.id):
                pending_local.append(i)
            else:
                remote.append(i)
        if pending_local:
            fut = self.loop.create_future()
            state = {"n": len(pending_local)}

            def _mk(i):
                def cb(obj):
                    def fire():
                        objs[i] = obj
                        state["n"] -= 1
                        if state["n"] == 0 and not fut.done():
                            fut.set_result(None)
                    # Most waiters resolve from the loop thread (reply
                    # ingestion); skip the self-pipe syscall there.
                    if threading.get_ident() == self._loop_thread_ident:
                        fire()
                    else:
                        self.loop.call_soon_threadsafe(fire)
                return cb

            for i in pending_local:
                self.memory_store.add_waiter(refs[i].id, _mk(i))
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                missing = next(i for i in pending_local
                               if objs[i] is None)
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for "
                    f"{refs[missing].id.hex()}")
        if remote:
            fetched = await asyncio.gather(
                *(self._fetch_from_owner(refs[i], timeout)
                  for i in remote))
            for i, obj in zip(remote, fetched):
                objs[i] = obj
        plasma = [i for i, obj in enumerate(objs)
                  if obj.metadata == IN_PLASMA]
        if plasma:
            opened = await asyncio.gather(
                *(self._open_shm(refs[i].id, timeout) for i in plasma))
            for i, obj in zip(plasma, opened):
                objs[i] = obj
        device = [i for i, obj in enumerate(objs)
                  if obj.metadata == serialization.DEVICE]
        if device:
            resolved = await asyncio.gather(
                *(self._resolve_device_object(refs[i], objs[i],
                                              donate=donate)
                  for i in device))
            out = [None] * len(objs)
            dset = set(device)
            for i, value in zip(device, resolved):
                out[i] = value
            for i, obj in enumerate(objs):
                if i not in dset:
                    out[i] = serialization.deserialize(
                        obj.metadata, obj.inband, obj.buffers)
            return out
        return [
            serialization.deserialize(obj.metadata, obj.inband,
                                      obj.buffers)
            for obj in objs
        ]

    async def get_async(self, ref: ObjectRef, timeout: Optional[float] = None,
                        donate: bool = False):
        obj = await self._resolve_object(ref, timeout)
        if obj.metadata == serialization.DEVICE:
            return await self._resolve_device_object(ref, obj,
                                                     donate=donate)
        return serialization.deserialize(obj.metadata, obj.inband, obj.buffers)

    async def _resolve_object(self, ref: ObjectRef,
                              timeout: Optional[float] = None
                              ) -> SerializedObject:
        object_id = ref.id
        obj = self.memory_store.get_if_exists(object_id)
        if obj is None:
            # Ownership is by ADDRESS first: a ref whose owner is another
            # process must be fetched from it even when the task-id
            # heuristic matches one of ours (e.g. an object ray.put() by
            # a still-running actor task we submitted — its task id is in
            # our pending set, but the object lives with the worker).
            owner = ref.owner_address
            owner_is_self = owner is None or owner.key() == self.address.key()
            if owner_is_self and self._owns(object_id):
                obj = await self._wait_local(object_id, timeout)
            else:
                obj = await self._fetch_from_owner(ref, timeout)
        if obj.metadata == IN_PLASMA:
            return await self._open_shm(object_id, timeout)
        return obj

    def _owns(self, object_id: ObjectID) -> bool:
        task_id = object_id.task_id()
        if task_id in self.pending_tasks:
            return True
        if task_id == self._root_task_id:
            return True  # driver-side puts
        return task_id in self._finished_task_ids

    def _ensure_sets(self):
        pass  # retained for call-site compatibility

    async def _wait_local(self, object_id: ObjectID,
                          timeout: Optional[float]) -> SerializedObject:
        fut = self.loop.create_future()

        def cb(obj):
            def fire():
                if not fut.done():
                    fut.set_result(obj)
            # Most waiters resolve from the loop thread itself (reply
            # ingestion); skip the self-pipe wakeup syscall there.
            if threading.get_ident() == self._loop_thread_ident:
                fire()
            else:
                self.loop.call_soon_threadsafe(fire)

        self.memory_store.add_waiter(object_id, cb)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise exc.GetTimeoutError(
                f"get() timed out waiting for {object_id.hex()}"
            )

    async def _fetch_from_owner(self, ref: ObjectRef,
                                timeout: Optional[float]) -> SerializedObject:
        owner = ref.owner_address
        if owner is None:
            # No owner info: assume shm (e.g. ref recreated from hex).
            return make_plasma_marker()
        try:
            conn = await self.get_connection(owner.key())
            reply = await conn.call(
                "get_object", {"object_id": ref.hex(), "timeout": timeout},
                timeout=timeout,
            )
        except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
            # Device-plane objects outlive their owner: the envelope +
            # sharding manifest are mirrored in the head's owner table,
            # and any registered holder can serve the shards (replica
            # cold-start-from-peer).
            fallback = await self._device_envelope_from_head(ref.id)
            if fallback is not None:
                return fallback
            raise exc.ObjectLostError(ref.hex()) from e
        if reply.get("in_plasma"):
            return make_plasma_marker()
        if not reply.get("found"):
            raise exc.GetTimeoutError(
                f"object {ref.hex()} not available from owner"
            )
        obj = SerializedObject(
            metadata=reply["metadata"], inband=reply["inband"],
            buffers=list(reply.get("buffers", [])),
        )
        # Cache small borrowed values locally.
        self.memory_store.put(ref.id, obj)
        return obj

    async def _open_shm(self, object_id: ObjectID,
                        timeout: Optional[float]) -> SerializedObject:
        obj = object_store.node_store_open(object_id)
        if obj is not None:
            return obj
        reply = await self.head.call(
            "wait_object", {"object_id": object_id.hex(), "timeout": timeout}
        )
        if not reply.get("sealed"):
            raise exc.GetTimeoutError(
                f"shm object {object_id.hex()} not sealed in time"
            )
        obj = object_store.node_store_open(object_id)
        if obj is None:
            # Sealed somewhere, but not in this node's store: pull it over
            # the network from a holder (reference: pull_manager.h:52).
            obj = await self._pull_remote(object_id)
        if obj is None:
            # Every copy is gone (evicted / worker died / segment deleted):
            # rebuild by resubmitting the creating task (reference:
            # object_recovery_manager.h:63-72).
            obj = await self._recover_object(object_id, timeout)
        if obj is None:
            raise exc.ObjectLostError(object_id.hex())
        return obj

    # ------------------------------------------------------------------
    # device-native object plane (core/device_objects.py)
    # ------------------------------------------------------------------

    async def _device_envelope_from_head(self, object_id: ObjectID
                                         ) -> Optional[SerializedObject]:
        """The mirrored envelope from the head's owner table (owner-death
        fallback). None when the object isn't a device-plane object or
        the envelope was too large to mirror."""
        try:
            reply = await self.head.call(
                "locate_device_object", {"object_id": object_id.hex()})
        except Exception:
            return None
        envelope = reply.get("envelope") if reply.get("found") else None
        if envelope is None:
            return None
        metadata, inband, buffers = envelope
        return SerializedObject(metadata=bytes(metadata),
                                inband=bytes(inband),
                                buffers=list(buffers or []))

    async def _resolve_device_object(self, ref: ObjectRef,
                                     obj: SerializedObject,
                                     donate: bool = False) -> Any:
        """Materialize a DEVICE envelope: placeholders become arrays —
        by reference when this process already holds them, otherwise via
        per-shard pulls from any registered holder."""
        from ray_tpu.core import device_objects

        value = serialization.deserialize(serialization.NORMAL,
                                          obj.inband, obj.buffers)
        leaf_refs = device_objects.collect_leaf_refs(value)
        resolved: Dict[Tuple[str, int], Any] = {}
        missing = []
        for lr in leaf_refs:
            arr = device_objects.local_array(lr.obj_hex, lr.leaf)
            if arr is not None:
                resolved[(lr.obj_hex, lr.leaf)] = arr
            else:
                missing.append(lr)
        if missing:
            # The owner registers the manifest asynchronously at put
            # time; a consumer racing that registration (publish →
            # immediate fetch) sees an empty holder list for a few ms —
            # retry briefly before declaring the object lost.
            holders = await self._device_holders(ref.id)
            for delay in self._probe_retry.backoff_series(3):
                if holders:
                    break
                if delay:
                    await asyncio.sleep(delay)
                holders = await self._device_holders(ref.id)
            if not holders:
                raise exc.ObjectLostError(
                    f"device object {ref.hex()}: no registered holders")
            sources = set()
            sem = asyncio.Semaphore(
                max(1, self.config.device_shard_pull_concurrency))
            # Leaves pull concurrently — a weights pytree of many
            # small-shard leaves would otherwise serialize on one
            # transfer at a time; the shared semaphore still bounds
            # total staging.
            pulled = await asyncio.gather(
                *(self._pull_device_leaf(ref.id, lr, holders, sem)
                  for lr in missing))
            servable = 0
            for lr, (arr, source) in zip(missing, pulled):
                sources.add(source)
                resolved[(lr.obj_hex, lr.leaf)] = arr
                servable += device_objects.register_assembled(
                    ref.id, lr.leaf, lr.desc, arr)
            if servable:
                # Become a holder: peers (e.g. the next cold-starting
                # replica) can now pull from this process. A consumer
                # that fell back to single-device assembly has no
                # shards matching the recorded layout — listing it
                # would only burn peers' pull sweeps.
                asyncio.ensure_future(self.head.call(
                    "device_location_added", {
                        "object_id": ref.id.hex(),
                        "holder": list(self._device_holder_address()),
                    }))
            else:
                device_objects.drop(ref.id.hex())
            if donate:
                for src in sources:
                    await self._donate_source_shards(ref.id, src)
        return device_objects.substitute(value, resolved)

    async def _device_holders(self, object_id: ObjectID) -> List[tuple]:
        try:
            reply = await self.head.call(
                "locate_device_object", {"object_id": object_id.hex()})
        except Exception:
            return []
        if not reply.get("found"):
            return []
        me = tuple(self._device_holder_address())
        return [tuple(h) for h in reply.get("holders", [])
                if tuple(h) != me]

    async def _pull_device_leaf(self, object_id: ObjectID, leaf_ref,
                                holders: List[tuple],
                                sem: asyncio.Semaphore,
                                preferred: Optional[tuple] = None):
        """Pull one leaf's shards (bounded concurrency, resumable range
        reads with chunked-rpc fallback) and assemble the array against
        the recorded sharding. Returns (array, holder that served it)."""
        from ray_tpu.core import device_objects
        from ray_tpu.util import flight_recorder, telemetry

        desc = leaf_ref.desc
        ordered = ([preferred] if preferred in holders else []) + [
            h for h in holders if h != preferred]
        last_error: Optional[Exception] = None
        loop = asyncio.get_running_loop()
        for holder in ordered:
            assembler = device_objects.LeafAssembler(desc)
            # Shared with the data-plane threads: a failed sibling sets
            # "stop" so blocked recv loops bail at their next check
            # instead of riding out the socket timeout.
            state = {"stop": False}
            try:
                async def pull_one(meta, holder=holder,
                                   assembler=assembler, state=state):
                    async with sem:
                        t0 = time.perf_counter()
                        buf = device_objects.StagingBuffer(meta["nbytes"])
                        absorbed = False
                        try:
                            sid = device_objects.shard_id(
                                object_id.binary(), leaf_ref.leaf,
                                meta["key"])
                            await self._pull_shard(holder, sid,
                                                   buf.view(), state)
                            # Land on device NOW and release the host
                            # staging before the next shard claims a
                            # buffer: peak host memory stays at
                            # concurrency × shard size. On XLA:CPU the
                            # landing may absorb the buffer zero-copy —
                            # then it belongs to the array, not the pool.
                            absorbed = await loop.run_in_executor(
                                None, assembler.land, meta["key"],
                                buf.array)
                        finally:
                            if absorbed:
                                buf.forfeit()
                            else:
                                buf.release()
                        elapsed = time.perf_counter() - t0
                        telemetry.observe(
                            "ray_tpu_object_shard_pull_seconds",
                            elapsed, {"status": "ok"})
                        telemetry.inc(
                            "ray_tpu_object_shard_pull_bytes_total",
                            meta["nbytes"])
                        flight_recorder.record(
                            "object", "shard_pulled",
                            object=object_id.hex()[:16],
                            leaf=leaf_ref.leaf, shard=meta["key"],
                            bytes=meta["nbytes"],
                            dur_s=round(elapsed, 4))

                tasks = [asyncio.ensure_future(pull_one(meta))
                         for meta in desc["shards"]]
                try:
                    await asyncio.gather(*tasks)
                except BaseException:
                    # One shard failed: siblings still in flight for
                    # THIS holder would otherwise keep the shared
                    # semaphore slots (and their sockets) busy for the
                    # retry against the next holder. Cancel and drain.
                    state["stop"] = True
                    for t in tasks:
                        t.cancel()
                    await asyncio.gather(*tasks, return_exceptions=True)
                    raise
                arr = await loop.run_in_executor(None,
                                                 assembler.finalize)
                return arr, holder
            except asyncio.CancelledError:
                raise
            except Exception as e:
                last_error = e
                telemetry.observe("ray_tpu_object_shard_pull_seconds",
                                  0.0, {"status": "error"})
                logger.info("device shard pull from %s failed: %s",
                            holder, e)
        raise exc.ObjectLostError(
            f"device object {object_id.hex()}: every holder failed "
            f"({last_error})")

    async def _pull_shard(self, holder: tuple, shard_id_bytes: bytes,
                          dest: memoryview,
                          state: Optional[dict] = None) -> None:
        """One shard from one holder: bulk data plane first (resumable
        range reads, two kernel copies), chunked rpc on the worker
        connection as the fallback. ``state["stop"]`` aborts the
        data-plane recv loop between reads (sibling-failure cleanup)."""
        host, port, data_port = holder
        if data_port:
            loop = asyncio.get_running_loop()
            try:
                await loop.run_in_executor(
                    None, object_transfer.pull_shard_into,
                    (host, data_port), shard_id_bytes, dest, state)
                return
            except object_transfer._PullAborted:
                raise
            except OSError as e:
                logger.info("shard data-plane pull from %s:%s failed "
                            "(%s); falling back to chunked rpc",
                            host, data_port, e)
        conn = await self.get_connection((host, port))
        total = dest.nbytes
        offset = 0
        while offset < total:
            ln = min(object_transfer.SHARD_CHUNK_BYTES, total - offset)
            reply = await conn.call("fetch_device_shard", {
                "shard_id": bytes(shard_id_bytes).hex(),
                "offset": offset, "length": ln,
            })
            if not reply.get("found"):
                raise object_transfer._PullAborted(
                    "holder no longer serves the shard")
            chunk = reply.get("__attachment__", b"")
            if len(chunk) != ln:
                raise object_transfer._PullAborted("truncated shard chunk")
            dest[offset:offset + ln] = chunk
            offset += ln

    async def _donate_source_shards(self, object_id: ObjectID,
                                    source: tuple) -> None:
        """donate=True epilogue: the consumer has the shards; tell the
        serving holder to release its device buffers (an HBM move, not a
        copy)."""
        host, port, _data_port = source
        try:
            conn = await self.get_connection((host, port))
            await conn.call("donate_device_shards",
                            {"object_id": object_id.hex()})
        except Exception as e:
            _swallow("device.donate_notify", e,
                     object=object_id.hex()[:16])

    async def h_fetch_device_shard(self, conn, payload):
        """Chunked-rpc shard serving (fallback when a puller can't reach
        the bulk data plane). Offset-based, so interrupted pulls resume."""
        from ray_tpu.core import device_objects

        view = device_objects.shard_view(
            bytes.fromhex(payload["shard_id"]))
        if view is None:
            return {"found": False}
        off = int(payload["offset"])
        ln = int(payload["length"])
        return rpc.WithAttachment(
            {"found": True, "total": view.nbytes}, view[off:off + ln])

    async def h_donate_device_shards(self, conn, payload):
        """A consumer finished a donate=True transfer: release this
        process's device buffers for the object and retract the holder
        listing."""
        from ray_tpu.core import device_objects
        from ray_tpu.util import flight_recorder

        hex_id = payload["object_id"]
        released = device_objects.drop(hex_id, donated=True)
        if released:
            flight_recorder.record("object", "shard_donated",
                                   object=hex_id[:16], bytes=released)
            try:
                await self.head.call("device_location_removed", {
                    "object_id": hex_id,
                    "holder": list(self._device_holder_address()),
                })
            except Exception as e:
                _swallow("device.donate_location_removed", e,
                         object=hex_id[:16])
        return {"ok": True, "released": released}

    async def _recover_object(self, object_id: ObjectID,
                              timeout: Optional[float]
                              ) -> Optional[SerializedObject]:
        entry = self._lineage.get(object_id)
        spec = entry[0] if entry is not None else None
        fut = (self._recovering.get(spec.task_id)
               if spec is not None else None)
        if fut is None:
            # A transient RPC blip to a live holder must not destroy
            # intact copies: object_lost below deletes the head's copy
            # and tells every holder to drop theirs, and the lineage
            # resubmit re-executes even max_retries=0 tasks. Re-probe
            # the directory and retry the pull first (reference:
            # object_recovery_manager.cc pins existing copies before
            # falling back to reconstruction). Bounded by the caller's
            # timeout, and skipped once the directory reports no copies
            # (then reconstruction is the only path).
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            # Probe FIRST: if the directory already reports zero
            # copies, reconstruction starts with no added latency; the
            # sleeps only buy time when copies allegedly exist.
            for delay in self._probe_retry.backoff_series(3):
                if (deadline is not None
                        and time.monotonic() + delay >= deadline):
                    break
                if delay:
                    await asyncio.sleep(delay)
                try:
                    reply = await self.head.call(
                        "locate_object", {"object_id": object_id.hex()})
                except Exception:
                    continue
                if not reply.get("found") or not reply.get("locations"):
                    break  # no copies exist anywhere: reconstruct
                if await self._delegate_or_pull(
                        object_id,
                        [tuple(a) for a in reply["locations"]]):
                    obj = object_store.node_store_open(object_id)
                    if obj is not None:
                        return obj
        if spec is None:
            return None
        fut = self._recovering.get(spec.task_id)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._recovering[spec.task_id] = fut
            logger.info("recovering lost object %s by resubmitting task %s",
                        object_id.hex()[:12], spec.name or
                        spec.task_id.hex()[:12])
            from ray_tpu.util import flight_recorder

            flight_recorder.record(
                "object", "lost", severity="error",
                object=object_id.hex()[:16],
                task=spec.task_id.hex()[:16], name=spec.name or "")
            # Reset terminal state so the reply path treats this as a
            # fresh attempt of the same task (same return object ids).
            self._finished_task_ids.discard(spec.task_id)
            self.pending_tasks[spec.task_id] = PendingTask(
                spec=spec, retries_left=max(spec.max_retries, 1))
            # Clear the stale seal record so wait_object below blocks
            # until the resubmitted task seals a fresh copy.
            try:
                await self.head.call("object_lost",
                                     {"object_id": object_id.hex()})
            except Exception as e:
                _swallow("recover.object_lost_notify", e,
                         object=object_id.hex()[:16])
            self._submit_on_loop(spec)

            async def wait_reseal(task_id=spec.task_id):
                try:
                    reply = await self.head.call("wait_object", {
                        "object_id": object_id.hex(),
                        "timeout": self.config.object_recovery_timeout_s,
                    })
                    ok = bool(reply.get("sealed"))
                except Exception:
                    ok = False
                f = self._recovering.pop(task_id, None)
                if f is not None and not f.done():
                    f.set_result(ok)

            asyncio.ensure_future(wait_reseal())
        try:
            ok = await asyncio.wait_for(asyncio.shield(fut), timeout)
        except asyncio.TimeoutError:
            raise exc.GetTimeoutError(
                f"timed out recovering object {object_id.hex()}")
        if not ok:
            return None
        obj = object_store.node_store_open(object_id)
        if obj is None:
            obj = await self._pull_remote(object_id)
        if obj is not None:
            from ray_tpu.util import flight_recorder

            flight_recorder.record("object", "recovered",
                                   object=object_id.hex()[:16])
        return obj

    async def _delegate_or_pull(self, object_id: ObjectID,
                                locations: list) -> bool:
        """Prefer pulling through the local node agent (reference: the
        raylet's pull manager owns pulls; workers read the result from
        shm): it coalesces concurrent workers' pulls and its long-lived
        mapping recycles warm extents. Direct pull is the fallback
        (head-host workers have no agent)."""
        import os as _os

        agent_port = _os.environ.get("RAY_TPU_AGENT_PORT")
        if agent_port:
            address = (_os.environ.get("RAY_TPU_AGENT_HOST",
                                       "127.0.0.1"), int(agent_port))
            try:
                conn = await self.get_connection(address)
                reply = await conn.call("pull_object", {
                    "object_id": object_id.hex(),
                    "locations": [list(a) for a in locations],
                })
                if reply.get("ok"):
                    return True
            except Exception:
                logger.info("agent pull delegation failed; pulling "
                            "directly", exc_info=True)
        return await self._puller.pull(object_id, locations)

    async def _pull_remote(self, object_id: ObjectID
                           ) -> Optional[SerializedObject]:
        try:
            reply = await self.head.call(
                "locate_object", {"object_id": object_id.hex()})
        except Exception:
            return None
        if not reply.get("found") or not reply.get("locations"):
            return None
        locations = [tuple(a) for a in reply["locations"]]
        if not await self._delegate_or_pull(object_id, locations):
            return None
        obj = object_store.node_store_open(object_id)
        if obj is not None and self.node_id_hex:
            # Tell the directory this node now holds a copy, so nearby
            # consumers pull locally instead of re-crossing the network.
            asyncio.ensure_future(self.head.call(
                "object_location_added",
                {"object_id": object_id.hex(),
                 "node_id": self.node_id_hex}))
        return obj

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        self._check_not_on_loop("wait()")
        fut = self.loop_thread.submit(
            self._wait_async(refs, num_returns, timeout)
        )
        return fut.result()

    async def _wait_async(self, refs, num_returns, timeout):
        ready: List[ObjectRef] = []
        pending = {
            asyncio.ensure_future(self._resolve_object(r)): r for r in refs
        }
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while pending and len(ready) < num_returns:
                remaining = None
                if deadline is not None:
                    remaining = max(0.0, deadline - time.monotonic())
                    if remaining == 0:
                        break
                done, _ = await asyncio.wait(
                    pending.keys(), timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
                if not done:
                    break
                for t in done:
                    ready.append(pending.pop(t))
        finally:
            for t in pending:
                t.cancel()
        not_ready = [r for r in refs if r not in ready]
        ready_sorted = [r for r in refs if r in ready][:num_returns]
        extra = [r for r in ready if r not in ready_sorted]
        return ready_sorted, not_ready + extra

    def free(self, refs: List[ObjectRef]):
        from ray_tpu.core import device_objects

        hex_ids = [r.hex() for r in refs]
        for ref in refs:
            self.memory_store.delete(ref.id)
            self._drop_lineage(ref.id)
            device_objects.drop(ref.hex())
        self.loop_thread.submit(
            self.head.call("free_objects", {"object_ids": hex_ids})
        )

    def _free_owned_object(self, object_id: ObjectID, in_shm: bool,
                           device: bool = False):
        self.memory_store.delete(object_id)
        self._drop_lineage(object_id)
        if device:
            from ray_tpu.core import device_objects

            device_objects.drop(object_id.hex())
            if not self._shutdown and not in_shm:
                # Device envelopes live in the memory store, so the shm
                # free below won't fire — still tell the head to drop
                # the manifest (and any stale holder entries with it).
                try:
                    self.loop_thread.submit(
                        self.head.call("free_objects",
                                       {"object_ids": [object_id.hex()]})
                    )
                except Exception as e:
                    _swallow("free.device_manifest_notify", e,
                             object=object_id.hex()[:16])
        if in_shm and not self._shutdown:
            from ray_tpu.util import flight_recorder

            flight_recorder.record("object", "freed",
                                   object=object_id.hex()[:16])
            try:
                self.loop_thread.submit(
                    self.head.call("free_objects",
                                   {"object_ids": [object_id.hex()]})
                )
            except Exception as e:
                _swallow("free.head_notify", e,
                         object=object_id.hex()[:16])

    def _release_borrowed_device_copy(self, object_id: ObjectID):
        """Final local release of a borrowed ref: if this process
        assembled a device copy (it was serving it to peers), drop the
        registry entry and retract the holder listing."""
        from ray_tpu.core import device_objects

        if not device_objects.holds(object_id.hex()):
            return
        device_objects.drop(object_id.hex())
        if self._shutdown:
            return
        try:
            self.loop_thread.submit(
                self.head.call("device_location_removed", {
                    "object_id": object_id.hex(),
                    "holder": list(self._device_holder_address()),
                }))
        except Exception as e:
            _swallow("device.location_removed_notify", e,
                     object=object_id.hex()[:16])

    def _notify_owner_ref_removed(self, object_id: ObjectID, owner: Address):
        if self._shutdown:
            return

        async def go():
            try:
                conn = await self.get_connection(owner.key())
                await conn.notify("remove_ref", {"object_id": object_id.hex()})
            except Exception as e:
                _swallow("borrow.remove_ref_notify", e,
                         object=object_id.hex()[:16])

        try:
            self.loop_thread.submit(go())
        except Exception as e:
            _swallow("borrow.remove_ref_submit", e,
                     object=object_id.hex()[:16])

    def _notify_owner_add_borrow(self, object_id: ObjectID, owner: Address):
        if self._shutdown:
            return
        # Epoch for the executor's sync-reply fast path: an add_borrow
        # queued during a task's execution must not be overtaken by a
        # raw-socket task_done (the owner could free the object before
        # learning of the borrow) — the executor compares this counter
        # around execution and falls back to the ordered loop path.
        self.owner_notify_epoch += 1

        async def go():
            try:
                conn = await self.get_connection(owner.key())
                await conn.notify("add_borrow", {"object_id": object_id.hex()})
            except Exception as e:
                _swallow("borrow.add_borrow_notify", e,
                         object=object_id.hex()[:16])

        try:
            self.loop_thread.submit(go())
        except Exception as e:
            _swallow("borrow.add_borrow_submit", e,
                     object=object_id.hex()[:16])

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        out = concurrent.futures.Future()

        def done_cb(task):
            if task.cancelled():
                out.cancel()
            elif task.exception() is not None:
                out.set_exception(task.exception())
            else:
                out.set_result(task.result())

        def schedule():
            t = asyncio.ensure_future(self.get_async(ref))
            t.add_done_callback(done_cb)

        self.loop.call_soon_threadsafe(schedule)
        return out

    # ------------------------------------------------------------------
    # serving owned objects
    # ------------------------------------------------------------------

    async def h_get_object(self, conn, payload):
        object_id = ObjectID.from_hex(payload["object_id"])
        obj = self.memory_store.get_if_exists(object_id)
        if obj is None and self._owns(object_id):
            try:
                obj = await self._wait_local(object_id,
                                             payload.get("timeout") or 30.0)
            except exc.GetTimeoutError:
                obj = None
        if obj is None:
            return {"found": False}
        if obj.metadata == IN_PLASMA:
            return {"found": True, "in_plasma": True}
        return {
            "found": True,
            "metadata": obj.metadata,
            "inband": obj.inband,
            "buffers": [bytes(memoryview(b)) for b in obj.buffers],
        }

    async def h_add_borrow(self, conn, payload):
        self.reference_counter.on_borrow_added(
            ObjectID.from_hex(payload["object_id"])
        )
        return {"ok": True}

    async def h_remove_ref(self, conn, payload):
        self.reference_counter.on_borrow_removed(
            ObjectID.from_hex(payload["object_id"])
        )
        return {"ok": True}

    async def h_ping(self, conn, payload):
        return {"ok": True}

    # ------------------------------------------------------------------
    # pubsub dispatch
    # ------------------------------------------------------------------

    async def h_pubsub(self, conn, payload):
        channel = payload["channel"]
        data = payload["data"]
        if channel == "actor_state":
            self._on_actor_state(data)
        elif channel in self._pubsub_callbacks:
            for cb in self._pubsub_callbacks[channel]:
                try:
                    cb(data)
                except Exception:
                    logger.exception("pubsub callback failed")
        return {"ok": True}

    _pubsub_callbacks: Dict[str, List[Callable]] = {}

    def subscribe(self, channel: str, callback: Callable):
        self._pubsub_callbacks.setdefault(channel, []).append(callback)
        self.loop_thread.submit(self.head.call("subscribe",
                                               {"channel": channel}))

    # ------------------------------------------------------------------
    # function table
    # ------------------------------------------------------------------

    def export_function(self, fn_or_class: Any) -> str:
        """Non-blocking: the KV put is fired asynchronously so this is safe
        to call from the event-loop thread itself (async actor methods
        submitting tasks). fetch_function retries to cover the put racing
        the first fetch."""
        cache_key = id(fn_or_class)
        key = self._exported_functions.get(cache_key)
        if key is not None:
            return key
        blob = serialization.dumps_control(fn_or_class)
        import hashlib

        digest = hashlib.sha256(blob).hexdigest()[:24]
        key = f"fn:{self.job_id.hex()}:{digest}"
        self.loop_thread.submit(
            self.head.call("kv_put", {
                "ns": "functions", "key": key.encode(), "value": blob,
                "overwrite": False,
            })
        )
        self._exported_functions[cache_key] = key
        self._function_cache[key] = fn_or_class
        return key

    async def fetch_function(self, key: str, timeout: float = 30.0) -> Any:
        fn = self._function_cache.get(key)
        if fn is not None:
            return fn
        try:
            reply = await self._rpc_retry.poll(
                lambda: self.head.call(
                    "kv_get", {"ns": "functions", "key": key.encode()}),
                predicate=lambda r: r.get("value") is not None,
                deadline_s=timeout, label=f"fetch_function {key[-12:]}")
        except retry.PollTimeout:
            raise exc.RayTpuError(f"function {key} not found in GCS")
        fn = serialization.loads_control(reply["value"])
        self._function_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # task submission (normal tasks)
    # ------------------------------------------------------------------

    def serialize_args(self, args: tuple, kwargs: dict) -> List[TaskArg]:
        """Args are packed as a single (args, kwargs) tuple argument when
        small; ObjectRefs are always passed by reference."""
        out: List[TaskArg] = []
        flat: List[Any] = list(args) + [kwargs]
        for value in flat:
            if isinstance(value, ObjectRef):
                # Register the borrow exactly as pickling the ref would;
                # the executor's reconstructed ref sends the matching
                # remove_ref when it is dropped.
                self.reference_counter.on_ref_serialized(value)
                out.append(TaskArg(object_id=value.id, owner=value.owner_address))
                continue
            obj = serialization.serialize(value)
            if obj.total_size() > self.config.max_direct_call_object_size:
                object_id = ObjectID.for_put(self.current_task_id(),
                                             self._put_counter.next())
                self.put_serialized(object_id, obj)
                out.append(TaskArg(object_id=object_id, owner=self.address))
            else:
                out.append(TaskArg(inline=(
                    obj.metadata, obj.inband,
                    [bytes(memoryview(b)) for b in obj.buffers],
                )))
        return out

    def submit_task(self, function_key: str, args: List[TaskArg], *,
                    name: str, num_returns: int, resources: Dict[str, float],
                    max_retries: int, retry_exceptions: bool,
                    scheduling_strategy, runtime_env=None,
                    stream_window: int = 0) -> List[ObjectRef]:
        self._ensure_sets()
        task_id = TaskID.for_normal_task(self.job_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=TaskType.NORMAL_TASK,
            name=name,
            function_key=function_key,
            args=args,
            num_returns=num_returns,
            resources=resources,
            owner=self.address,
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            scheduling_strategy=scheduling_strategy,
            runtime_env=runtime_env,
            stream_window=stream_window,
        )
        self.pending_tasks[task_id] = PendingTask(
            spec=spec, retries_left=max_retries
        )
        from ray_tpu.util import telemetry

        telemetry.inc("ray_tpu_tasks_total", 1, {"state": "SUBMITTED"})
        if num_returns == TaskSpec.STREAMING:
            gen = ObjectRefGenerator(
                task_id, cleanup=lambda: self._release_stream(task_id))
            self._streams[task_id] = gen
            self._submit_threadsafe(spec)
            return gen
        refs = [
            ObjectRef(oid, self.address, is_owned=True)
            for oid in spec.return_object_ids()
        ]
        # Ownership starts at SUBMIT, not at result ingest: a ref
        # serialized into another task's args while this task is still
        # running must take the owned +1-borrow path locally. Routing it
        # through a self-RPC lets the submitter's own ref GC race in a
        # spurious remove_ref and free the value under the borrower
        # (reference: reference_count.h owns from task submission).
        for oid in spec.return_object_ids():
            self.reference_counter.register_owned(oid, False)
        self._submit_threadsafe(spec)
        return refs

    def _submit_threadsafe(self, spec: TaskSpec):
        """Queue a spec for the loop with one wakeup per burst: rapid
        submissions from an API thread coalesce onto a single self-pipe
        write instead of one syscall each."""
        with self._submit_lock:
            self._submit_buf.append(spec)
            if self._submit_wake_pending:
                return
            self._submit_wake_pending = True
        self.loop.call_soon_threadsafe(self._drain_submits)

    def _drain_submits(self):
        with self._submit_lock:
            specs, self._submit_buf = self._submit_buf, []
            self._submit_wake_pending = False
        # Queue everything first, pump once per scheduling key — a
        # 100-task burst otherwise pays 100 pump scans for one batch.
        touched: Dict[int, tuple] = {}
        for spec in specs:
            key = spec.scheduling_key()
            state = self.scheduling_keys.setdefault(
                key, SchedulingKeyState())
            state.queue.append(spec)
            touched[id(state)] = (key, state)
        for key, state in touched.values():
            self._pump_scheduling_key(key, state)

    def _submit_on_loop(self, spec: TaskSpec):
        key = spec.scheduling_key()
        state = self.scheduling_keys.setdefault(key, SchedulingKeyState())
        state.queue.append(spec)
        self._pump_scheduling_key(key, state)

    def _pump_scheduling_key(self, key: tuple, state: SchedulingKeyState):
        # Push queued tasks onto leased workers, keeping each worker's
        # pipeline fed up to the in-flight cap (the worker executes FIFO;
        # queued pushes hide the RTT behind execution). A burst drains as
        # ONE batched RPC per worker — at tiny-task rates the msgpack
        # envelope + loop wakeups per frame are the throughput ceiling.
        cap = max(1, self.config.max_tasks_in_flight_per_worker)
        avail = [lw for lw in state.workers.values()
                 if lw.conn is not None and not lw.conn.closed
                 and lw.busy < cap]
        # Even split across available workers: a burst becomes one big
        # frame per worker (frame-cost amortization) without piling the
        # whole queue onto the first worker (load-imbalance bound).
        remaining = len(avail)
        for lw in avail:
            if not state.queue:
                break
            share = -(-len(state.queue) // remaining)  # ceil
            remaining -= 1
            n = min(cap - lw.busy, share)
            if n <= 0:
                continue
            batch: List[TaskSpec] = []
            while state.queue and len(batch) < n:
                batch.append(state.queue.popleft())
            if batch:
                self._push_tasks_to_worker(key, state, lw, batch)
        # Request more leases if there is a backlog.
        limit = self.config.max_pending_lease_requests_per_scheduling_category
        backlog = len(state.queue)
        while backlog > 0 and state.inflight_lease_requests < min(limit, backlog):
            state.inflight_lease_requests += 1
            asyncio.ensure_future(self._request_lease(key, state))
            backlog -= 1

    async def _request_lease(self, key: tuple, state: SchedulingKeyState):
        try:
            if not state.queue:
                return
            spec = state.queue[0]
            reply = await self.head.call(
                "request_lease",
                {"spec": serialization.dumps_control(spec)},
            )
            if not reply.get("granted"):
                if reply.get("infeasible"):
                    # Fail every queued task under this key.
                    while state.queue:
                        s = state.queue.popleft()
                        self._store_task_error(
                            s,
                            exc.RayTpuError(
                                reply.get("error", "infeasible resource request")
                            ),
                        )
                return
            worker_id = WorkerID.from_hex(reply["worker_id"])
            address = (reply["host"], reply["port"])
            try:
                conn = await self.get_connection(address)
            except Exception:
                # Granted a worker we can't reach (e.g. it died and the
                # head hadn't noticed when it re-idled it). Hand the lease
                # back; the finally-pump below re-requests.
                await self.head.call("return_worker", {
                    "lease_id": reply["lease_id"],
                    "worker_id": reply["worker_id"],
                })
                return
            lw = LeasedWorker(
                worker_id=worker_id, address=address,
                lease_id=reply["lease_id"], conn=conn,
                idle_since=time.monotonic(),
            )
            state.workers[worker_id] = lw
            self._pump_scheduling_key(key, state)
            if lw.busy == 0:
                asyncio.ensure_future(self._maybe_return_lease(key, state, lw))
        finally:
            state.inflight_lease_requests -= 1
            # Re-pump AFTER the inflight decrement: a pump run from inside
            # the body still counts this request as inflight and will
            # refuse to issue a replacement, stranding queued tasks when
            # this request failed (dead-worker grant, head error, raced
            # queue). Harmless when the queue is empty.
            if state.queue and not self._shutdown:
                self._pump_scheduling_key(key, state)

    def _push_tasks_to_worker(self, key: tuple, state: SchedulingKeyState,
                              lw: LeasedWorker, specs: List[TaskSpec]):
        """One batched frame out; per-task ``task_done`` notifications
        back (h_task_done). Outstanding entries double as the failure
        ledger: a worker-connection close fails exactly the tasks whose
        results haven't arrived."""
        live: List[TaskSpec] = []
        for spec in specs:
            pending = self.pending_tasks.get(spec.task_id)
            if pending is None or pending.cancelled:
                continue
            pending.pushed_to = lw.worker_id
            pending.accepted = False
            live.append(spec)
        if not live:
            return
        # Serialize before anything is marked outstanding: a bad spec
        # (dumps_control raising) must fail only ITS task, not be
        # mistaken for a dead connection and fail the whole worker.
        blobs: List[bytes] = []
        sendable: List[TaskSpec] = []
        for spec in live:
            try:
                blobs.append(serialization.dumps_control(spec))
                sendable.append(spec)
            except Exception as e:  # noqa: BLE001
                self._fail_spec_locally(spec, e)
        if not sendable:
            return
        lw.busy += len(sendable)
        conn = lw.conn
        for spec in sendable:
            self._outstanding_pushes[spec.task_id.hex()] = (
                "task", spec, lw, key, state, conn)

        async def push():
            try:
                # Non-idempotent: the policy only retries a frame that
                # provably never left this process (ConnectionLost with
                # sent=False — closed transport or injected partition).
                # A connection that actually died fails fast to the
                # requeue machinery instead of burning backoff in place.
                await self._rpc_retry.execute(
                    lambda: conn.notify("push_tasks", {"specs": blobs}),
                    idempotent=False,
                    should_retry=lambda e: not conn.closed,
                    label="push_tasks")
            except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
                self._fail_worker_conn(conn, e)

        asyncio.ensure_future(push())

    def _fail_worker_conn(self, conn, error: Exception):
        """Fail every outstanding push on a dead worker connection."""
        dead = [hex_id for hex_id, entry in self._outstanding_pushes.items()
                if entry[-1] is conn]
        seen_lw = set()
        for hex_id in dead:
            entry = self._outstanding_pushes.pop(hex_id, None)
            if entry is None:
                continue
            if entry[0] == "task":
                _, spec, lw, key, state, _ = entry
                lw.busy -= 1
                if id(lw) not in seen_lw:
                    seen_lw.add(id(lw))
                    if state.workers.get(lw.worker_id) is lw:
                        state.workers.pop(lw.worker_id, None)
                        # Hand the lease back so the head can release its
                        # resources even before it notices the death.
                        asyncio.ensure_future(
                            self._return_lease_quietly(lw))
                self._on_task_worker_failure(spec, error)
            else:
                _, spec, astate, _ = entry
                astate.inflight -= 1
                self._on_actor_call_failure(astate, spec, error)

    def h_task_done(self, conn, payload):
        # Sync notification handler (rpc fast path: no Task per frame).
        entry = self._outstanding_pushes.pop(payload["task_id"], None)
        if entry is None:
            return  # already failed via connection close, or cancelled
        reply = payload["reply"]
        if "spec_decode_error" in reply:
            # The worker couldn't even decode the spec — it has no
            # return ids to package an error into, but we (the owner)
            # still hold the spec; resolve its returns here.
            self._store_task_error(
                entry[1], exc.RayTpuError(
                    f"worker failed to decode task spec for "
                    f"{entry[1].name}: {reply['spec_decode_error']}"))
            reply = {"returns": [], "is_error": True,
                     "_resolved_locally": True}
        if entry[0] == "task":
            _, spec, lw, key, state, _ = entry
            lw.busy -= 1
            lw.idle_since = time.monotonic()
            self._on_task_reply(spec, reply)
            self._pump_scheduling_key(key, state)
            if lw.busy == 0 and not state.queue:
                asyncio.ensure_future(
                    self._maybe_return_lease(key, state, lw))
        else:
            _, spec, astate, _ = entry
            astate.inflight -= 1
            self._on_task_reply(spec, reply)

    # ------------------------------------------------------------------
    # task events (reference: core_worker/task_event_buffer.h -> the
    # GCS task-event store; backend of the state API / timeline)
    # ------------------------------------------------------------------

    def record_task_event(self, spec, state: str):
        from ray_tpu.util import telemetry

        telemetry.inc("ray_tpu_tasks_total", 1, {"state": state})
        event = {
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "type": spec.task_type.name
            if hasattr(spec.task_type, "name") else str(spec.task_type),
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
            "worker_id": self.worker_id.hex(),
            "state": state,
            "ts": time.time(),
        }
        with self._task_event_lock:
            self._task_event_buf.append(event)
            size = len(self._task_event_buf)
        if size >= 100:
            self._flush_task_events()
        elif not self._event_flush_scheduled:
            # Benignly racy read; avoids a cross-thread loop wakeup per
            # event when a flush timer is already pending.
            self.loop.call_soon_threadsafe(self._schedule_event_flush)

    def _schedule_event_flush(self):
        if self._event_flush_scheduled:
            return
        self._event_flush_scheduled = True

        async def flush_later():
            await asyncio.sleep(
                self.config.task_events_report_interval_s)
            self._event_flush_scheduled = False
            self._flush_task_events()

        asyncio.ensure_future(flush_later())

    def _flush_task_events(self):
        with self._task_event_lock:
            if not self._task_event_buf:
                return
            events, self._task_event_buf = self._task_event_buf, []

        async def send():
            try:
                await self.head.call("report_task_events",
                                     {"events": events})
            except Exception as e:
                _swallow("task_events.flush", e, dropped=len(events))

        self.loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(send()))

    async def _return_lease_quietly(self, lw: "LeasedWorker"):
        try:
            await self.head.call("return_worker", {
                "lease_id": lw.lease_id,
                "worker_id": lw.worker_id.hex(),
            })
        except Exception:
            # Head unreachable or already aware of the death; it releases
            # the lease itself on worker-death detection.
            logger.debug("return_worker for %s failed", lw.lease_id)

    async def _maybe_return_lease(self, key: tuple, state: SchedulingKeyState,
                                  lw: LeasedWorker):
        await asyncio.sleep(self.config.idle_worker_lease_timeout_s)
        if lw.busy > 0 or state.queue:
            return
        # Identity check before popping: the same worker may have been
        # re-leased under this key after an earlier idle-timer returned it,
        # in which case state.workers[worker_id] is a *newer* LeasedWorker
        # record. A stale timer popping that record by worker_id alone would
        # orphan the new lease (nobody left to return it) and leak its
        # resources at the head forever.
        if state.workers.get(lw.worker_id) is not lw:
            return
        state.workers.pop(lw.worker_id, None)
        try:
            await self.head.call("return_worker", {
                "lease_id": lw.lease_id,
                "worker_id": lw.worker_id.hex(),
            })
        except Exception as e:
            # A dropped return leaks the lease until the head reaps the
            # worker — exactly the kind of slow leak the recorder must
            # witness.
            _swallow("lease.return_worker", e,
                     worker=lw.worker_id.hex()[:16])

    def _record_lineage(self, spec: TaskSpec, reply: dict):
        """Retain the creating-task spec of plasma-sealed returns so a
        lost copy can be rebuilt by resubmission. Only deterministic
        normal tasks qualify (re-running an actor method would replay
        side effects)."""
        if spec.task_type != TaskType.NORMAL_TASK:
            return
        if not any(r.get("in_plasma") for r in reply.get("returns", [])):
            return
        try:
            nbytes = len(serialization.dumps_control(spec))
        except Exception:
            return
        for ret in reply["returns"]:
            if ret.get("in_plasma"):
                oid = ObjectID(ret["object_id"])
                if oid not in self._lineage:
                    self._lineage[oid] = (spec, nbytes)
                    self._lineage_bytes += nbytes
        while (self._lineage_bytes > self.config.max_lineage_bytes
               and self._lineage):
            _, (_, evicted_bytes) = self._lineage.popitem(last=False)
            self._lineage_bytes -= evicted_bytes

    def _drop_lineage(self, object_id: ObjectID):
        entry = self._lineage.pop(object_id, None)
        if entry is not None:
            self._lineage_bytes -= entry[1]

    def _on_task_reply(self, spec: TaskSpec, reply: dict):
        pending = self.pending_tasks.pop(spec.task_id, None)
        self._ensure_sets()
        self._finished_task_ids.add(spec.task_id)
        if len(self._finished_task_ids) > self.config.max_lineage_entries:
            self._finished_task_ids.clear()
        self._record_lineage(spec, reply)
        is_app_error = reply.get("is_error", False)
        if is_app_error and pending is not None and spec.retry_exceptions \
                and pending.retries_left > 0:
            pending.retries_left -= 1
            self.pending_tasks[spec.task_id] = pending
            self._finished_task_ids.discard(spec.task_id)
            self._submit_on_loop(spec)
            return
        for ret in reply.get("returns", []):
            self._ingest_return(ret)
        if "stream_count" in reply:
            gen = self._streams.get(spec.task_id)
            if gen is not None:
                err = None
                if is_app_error:
                    err = exc.RayTpuError(
                        f"streaming task {spec.name} failed")
                    ep = reply.get("error_payload")
                    if ep is not None:
                        try:
                            err = serialization.deserialize_no_raise(
                                ep["metadata"], ep["inband"],
                                ep.get("buffers", []))[0]
                        except Exception as e:
                            # Fall back to the generic stream error.
                            _swallow("stream.error_payload_decode", e,
                                     task=spec.task_id.hex()[:16])
                gen._finish(total=reply["stream_count"], error=err)

    def _fail_spec_locally(self, spec: TaskSpec, error: Exception):
        """Resolve a task's returns with an error that happened before
        the spec ever left this process (e.g. dumps_control raised) —
        the shape mirrors the worker's _package_error reply so gets
        raise instead of hanging."""
        obj = serialization.serialize_error(
            exc.RayTpuError(
                f"task spec for {spec.name} could not be serialized: "
                f"{type(error).__name__}: {error}"),
            task_name=spec.name)
        bufs = [bytes(memoryview(b)) for b in obj.buffers]
        if spec.num_returns == TaskSpec.STREAMING:
            reply = {
                "returns": [], "is_error": True, "stream_count": 0,
                "error_payload": {"metadata": obj.metadata,
                                  "inband": obj.inband, "buffers": bufs},
            }
        else:
            reply = {
                "returns": [
                    {"object_id": oid.binary(), "metadata": obj.metadata,
                     "inband": obj.inband, "buffers": bufs}
                    for oid in spec.return_object_ids()],
                "is_error": True,
            }
        self._on_task_reply(spec, reply)

    def _on_task_worker_failure(self, spec: TaskSpec, error: Exception):
        pending = self.pending_tasks.get(spec.task_id)
        if pending is None:
            return
        # Free-retry decision. Two signals:
        # - error.sent is False: the push was never written to the socket,
        #   so the task PROVABLY never ran — always safe to requeue.
        # - ack missing (pending.accepted False): the worker died before
        #   user code started OR within the executor's deferred-ack
        #   window (ACK_DELAY, worker_main) — either way execution
        #   lasted <~20ms; honor strict at-most-once for max_retries=0
        #   tasks by not using it there.
        provably_unsent = getattr(error, "sent", True) is False
        likely_unstarted = (not pending.accepted
                            and spec.max_retries != 0)
        # Streaming tasks: only a provably-unsent push may re-run — once
        # execution may have started, chunks may have reached the
        # registered stream and a re-run would replay them (api.py
        # already forces max_retries=0 for streaming; this guards direct
        # submit_task callers too).
        streaming = spec.num_returns == TaskSpec.STREAMING
        if ((provably_unsent or (likely_unstarted and not streaming))
                and not pending.cancelled and pending.free_retries > 0):
            pending.free_retries -= 1
            pending.pushed_to = None
            self._submit_on_loop(spec)
            return
        if pending.retries_left > 0 and not pending.cancelled \
                and not streaming:
            pending.retries_left -= 1
            pending.pushed_to = None
            logger.info("retrying task %s after worker failure",
                        spec.name or spec.task_id.hex()[:12])
            self._submit_on_loop(spec)
        else:
            # Ask the head whether this was a memory-monitor kill so the
            # terminal error names the cause (reference: raylet attaches
            # the OOM-killer detail to the task failure).
            worker_hex = (pending.pushed_to.hex()
                          if pending.pushed_to else None)

            async def finalize():
                reason = None
                if worker_hex is not None:
                    # The kill reason races this query: a node agent's
                    # report_oom_kill travels to the head concurrently
                    # with the dead worker's TCP reset reaching us.
                    for delay in self._probe_retry.backoff_series(3):
                        if delay:
                            await asyncio.sleep(delay)
                        try:
                            reply = await asyncio.wait_for(
                                self.head.call(
                                    "worker_death_reason",
                                    {"worker_id": worker_hex}),
                                timeout=5)
                            reason = reply.get("reason")
                        except Exception:
                            reason = None
                        if reason:
                            break
                if reason and "memory monitor" in reason:
                    err: Exception = exc.OutOfMemoryError(
                        f"task {spec.name} failed: {reason}")
                else:
                    err = exc.WorkerCrashedError(
                        f"worker died while running task {spec.name}: "
                        f"{error}" + (f" ({reason})" if reason else ""))
                self._store_task_error(spec, err)

            asyncio.ensure_future(finalize())

    def _store_task_error(self, spec: TaskSpec, error: Exception):
        self.pending_tasks.pop(spec.task_id, None)
        self._ensure_sets()
        self._finished_task_ids.add(spec.task_id)
        gen = self._streams.get(spec.task_id)
        if gen is not None:
            gen._finish(total=len(gen._items), error=error)
        obj = serialization.serialize_error(error, task_name=spec.name)
        for oid in spec.return_object_ids():
            self.memory_store.put(oid, obj)
            self.reference_counter.register_owned(oid, False)

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        task_id = ref.id.task_id()
        pending = self.pending_tasks.get(task_id)
        if pending is None:
            return

        def go():
            pending.cancelled = True
            # Remove from any queue.
            for key, state in self.scheduling_keys.items():
                try:
                    state.queue.remove(pending.spec)
                    self._store_task_error(
                        pending.spec, exc.TaskCancelledError(
                            f"task {pending.spec.name} cancelled"
                        )
                    )
                    return
                except ValueError:
                    continue
            # Already pushed: ask the worker to interrupt.
            if pending.pushed_to is not None:
                for state in self.scheduling_keys.values():
                    lw = state.workers.get(pending.pushed_to)
                    if lw is not None:
                        lw.conn.notify_forget(
                            "cancel_task",
                            {"task_id": pending.spec.task_id.hex(),
                             "force": force},
                        )
                        return

        self.loop.call_soon_threadsafe(go)

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------

    def create_actor(self, class_key: str, args: List[TaskArg], *,
                     name: str, actor_name: str, namespace: str,
                     resources: Dict[str, float], max_restarts: int,
                     max_task_retries: int, max_concurrency: int,
                     is_async: bool, scheduling_strategy,
                     runtime_env=None, detached: bool = False) -> ActorID:
        self._ensure_actor_subscription()
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_creation(actor_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=TaskType.ACTOR_CREATION_TASK,
            name=name,
            function_key=class_key,
            args=args,
            num_returns=1,
            resources=resources,
            owner=self.address,
            scheduling_strategy=scheduling_strategy,
            runtime_env=runtime_env,
            actor_id=actor_id,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            is_async_actor=is_async,
            actor_name=actor_name,
            namespace=namespace,
        )
        spec.detached = detached  # dynamic field, carried in pickle
        state = ActorState(actor_id=actor_id,
                           max_task_retries=max_task_retries)
        self.actors[actor_id] = state
        # __init__ failures surface as actor DEAD with the traceback in
        # death_cause; method calls then raise ActorDiedError.

        async def register():
            reply = await self.head.call(
                "register_actor",
                {"spec": serialization.dumps_control(spec)},
            )
            if not reply.get("ok"):
                state.state = "DEAD"
                state.death_cause = reply.get("error", "registration failed")
                self._fail_actor_queue(state)

        self.loop_thread.submit(register())
        return actor_id

    def _ensure_actor_subscription(self):
        if self._actor_sub_started:
            return
        self._actor_sub_started = True
        self.loop_thread.submit(self.head.call("subscribe",
                                               {"channel": "actor_state"}))

    def _on_actor_state(self, data: dict):
        actor_id = ActorID.from_hex(data["actor_id"])
        state = self.actors.get(actor_id)
        if state is None:
            state = ActorState(actor_id=actor_id)
            self.actors[actor_id] = state
        new_state = data["state"]
        state.state = new_state
        state.death_cause = data.get("death_cause", "")
        if data.get("address"):
            host, port, widhex = data["address"]
            state.address = Address(host, port, widhex)
        else:
            state.address = None
            state.conn = None
        if new_state == "ALIVE":
            asyncio.ensure_future(self._drain_actor_queue(state))
        elif new_state == "DEAD":
            self._fail_actor_queue(state)

    async def _drain_actor_queue(self, state: ActorState):
        if state.address is None:
            return
        try:
            state.conn = await self.get_connection(state.address.key())
        except Exception as e:
            logger.warning("connect to actor %s failed: %s",
                           state.actor_id.hex()[:12], e)
            self._ensure_actor_poller(state)  # re-drive via reconciliation
            return
        while state.queue and state.state == "ALIVE":
            spec = state.queue.popleft()
            self._push_actor_task(state, spec)

    def _ensure_actor_poller(self, state: ActorState):
        """Reconcile queued calls against the head's actor table. Pubsub
        delivery can race the subscription (e.g. a driver reconnecting
        after a head restart subscribes while the recreated actor flips
        to ALIVE), so parked tasks must never depend on catching the
        state event — poll until the queue drains or the actor dies
        (reference: core_worker's actor_task_submitter resubscribing via
        GetActorInfo on reconnect)."""
        if state.poller is not None and not state.poller.done():
            return

        async def poll():
            # Refresh FIRST: the common case is not a slow actor but a
            # subscription race — the actor flipped ALIVE before this
            # driver's pubsub subscription landed (prestarted workers
            # make creation near-instant), and sleeping first taxed
            # every first call to a fresh actor ~0.5 s.
            while (state.queue and state.state != "DEAD"
                   and not self._shutdown):
                try:
                    await self._refresh_actor_info(state.actor_id)
                except Exception:  # lint: allow-silent(head briefly unreachable; 0.5s poll loop retries and recording every miss would spam the ring)
                    pass
                if not state.queue:
                    return
                await asyncio.sleep(0.5)

        state.poller = asyncio.ensure_future(poll())

    def _fail_actor_queue(self, state: ActorState):
        while state.queue:
            spec = state.queue.popleft()
            self._store_task_error(
                spec, exc.ActorDiedError(state.actor_id.hex(),
                                         state.death_cause)
            )

    def submit_actor_task(self, actor_id: ActorID, method_name: str,
                          args: List[TaskArg], *, num_returns: int,
                          name: str = "", stream_window: int = 0):
        self._ensure_sets()
        state = self.actors.get(actor_id)
        if state is None:
            # Handle deserialized in another process; subscribe lazily.
            self._ensure_actor_subscription()
            state = ActorState(actor_id=actor_id)
            self.actors[actor_id] = state
            self.loop_thread.submit(self._refresh_actor_info(actor_id))
        task_id = TaskID.for_actor_task(actor_id)
        spec = TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=TaskType.ACTOR_TASK,
            name=name or method_name,
            function_key="",
            args=args,
            num_returns=num_returns,
            resources={},
            owner=self.address,
            actor_id=actor_id,
            method_name=method_name,
            stream_window=stream_window,
        )
        streaming = num_returns == TaskSpec.STREAMING
        self.pending_tasks[task_id] = PendingTask(
            spec=spec,
            # A streaming method may have delivered chunks before dying;
            # transparently re-running it would replay them. Mid-stream
            # failures are terminal (reference: streaming generators are
            # not retryable mid-stream).
            retries_left=0 if streaming else state.max_task_retries,
        )
        gen = None
        if streaming:
            gen = ObjectRefGenerator(
                task_id, cleanup=lambda: self._release_stream(task_id))
            self._streams[task_id] = gen
            refs = gen
        else:
            refs = [
                ObjectRef(oid, self.address, is_owned=True)
                for oid in spec.return_object_ids()
            ]
            # Owned from submit — see submit_task for why.
            for oid in spec.return_object_ids():
                self.reference_counter.register_owned(oid, False)

        def go():
            spec.seqno = state.seqno
            state.seqno += 1
            if state.state == "ALIVE" and state.conn is not None \
                    and not state.conn.closed:
                self._push_actor_task(state, spec)
            elif state.state == "DEAD":
                self._store_task_error(
                    spec, exc.ActorDiedError(actor_id.hex(), state.death_cause)
                )
            else:
                state.queue.append(spec)
                self._ensure_actor_poller(state)

        self.loop.call_soon_threadsafe(go)
        return refs

    def _on_actor_state_threadsafe(self, data: dict):
        self.loop.call_soon_threadsafe(self._on_actor_state, data)

    async def _refresh_actor_info(self, actor_id: ActorID):
        reply = await self.head.call("get_actor_info",
                                     {"actor_id": actor_id.hex()})
        if reply.get("found"):
            self._on_actor_state(reply)

    def _push_actor_task(self, state: ActorState, spec: TaskSpec):
        """Buffer the call; all calls submitted in the same loop tick go
        out as ONE batched frame (the worker executes them FIFO — per-
        actor ordering rides the buffer order, which follows seqno)."""
        state.push_buf.append(spec)
        if state.push_flush_scheduled:
            return
        state.push_flush_scheduled = True
        self.loop.call_soon(self._flush_actor_pushes, state)

    def _flush_actor_pushes(self, state: ActorState):
        state.push_flush_scheduled = False
        specs, state.push_buf = state.push_buf, []
        if not specs:
            return
        if state.conn is None or state.conn.closed:
            for spec in specs:
                self._on_actor_call_failure(
                    state, spec, rpc.ConnectionLost("actor connection"))
            return
        # Serialize up front: one bad spec fails only itself — treating
        # a local dumps_control error as a dead connection would fail
        # every outstanding call on this (healthy) actor.
        blobs: List[bytes] = []
        sendable: List[TaskSpec] = []
        for spec in specs:
            try:
                blobs.append(serialization.dumps_control(spec))
                sendable.append(spec)
            except Exception as e:  # noqa: BLE001
                self._fail_spec_locally(spec, e)
        if not sendable:
            return
        state.inflight += len(sendable)
        conn = state.conn
        for spec in sendable:
            self._outstanding_pushes[spec.task_id.hex()] = (
                "actor", spec, state, conn)

        async def push():
            try:
                # sent=False-only retries (see _push_tasks_to_worker):
                # a scripted partition heals in place with backoff; a
                # dead actor connection falls through to the park/retry
                # state machine immediately.
                await self._rpc_retry.execute(
                    lambda: conn.notify("push_tasks", {"specs": blobs}),
                    idempotent=False,
                    should_retry=lambda e: not conn.closed,
                    label="actor push_tasks")
            except (rpc.ConnectionLost, rpc.RpcError, OSError) as e:
                self._fail_worker_conn(conn, e)

        asyncio.ensure_future(push())

    def _on_actor_call_failure(self, state: ActorState, spec: TaskSpec,
                               error: Exception):
        pending = self.pending_tasks.get(spec.task_id)
        if pending is None:
            return
        if spec.num_returns == TaskSpec.STREAMING:
            # Chunks may already have reached the consumer — parking or
            # retrying would replay them. Surface a terminal error on
            # the stream NOW (the generator raises after the delivered
            # prefix instead of hanging).
            self._store_task_error(
                spec,
                exc.ActorDiedError(state.actor_id.hex(),
                                   state.death_cause or str(error)),
            )
            return
        if state.max_task_retries != 0 and pending.retries_left != 0:
            pending.retries_left -= 1
            state.queue.append(spec)  # retried when actor is ALIVE again
            self._ensure_actor_poller(state)
            return
        # If the actor may restart, park the call; otherwise fail it.
        if state.state in ("RESTARTING", "PENDING"):
            state.queue.append(spec)
            self._ensure_actor_poller(state)
        else:
            self._store_task_error(
                spec,
                exc.ActorDiedError(state.actor_id.hex(),
                                   state.death_cause or str(error)),
            )

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.loop_thread.run(
            self.head.call("kill_actor", {
                "actor_id": actor_id.hex(), "no_restart": no_restart,
            })
        )

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    async def stop(self):
        self._shutdown = True
        self.reference_counter.disable()
        if self.server:
            await self.server.stop()
        for conn in self._conn_cache.values():
            await conn.close()
        self._conn_cache.clear()
