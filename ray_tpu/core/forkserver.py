"""Worker forkserver: amortize interpreter + import startup across
worker processes.

Reference: the reference's worker pool (src/ray/raylet/worker_pool.cc)
pays process startup per worker and mitigates with prestarted idle
workers. In this runtime the dominant spawn cost is Python imports
(~2.5 s: numpy + the runtime modules on this class of host), so each
node runs ONE forkserver process that preimports the worker module and
``fork()``s per spawn request — worker spawn drops from seconds to
milliseconds, which is the difference between ~1 actor/s and tens of
actors/s in the many_actors scale lane.

Protocol (unix socket, one JSON line each way):
  {"env": {...}, "log_path": "..."}  ->  {"pid": N} | {"error": "..."}
  {"op": "shutdown"}                 ->  {"ok": true}

Fork safety: the server stays single-threaded and never initializes
any backend (no jax device init, no event loops) before forking; the
preimport is module code only. Children ``setsid`` and redirect
stdout/stderr to their log file, then run ``worker_main.main()`` which
reads its identity from the env vars set post-fork. SIGCHLD is
SIG_IGNed so exited workers are auto-reaped (no zombies); liveness is
probed with ``kill(pid, 0)``. POSIX-only — ``RAY_TPU_FORKSERVER=0``
(or any spawn error) falls back to the plain Popen path.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, Optional


def serve(sock_path: str, owner_pid: Optional[int] = None) -> None:
    """Forkserver main loop (runs as a dedicated process). owner_pid is
    the process whose death should take this forkserver down (passed
    explicitly: by the time our own ppid is sampled we may already have
    been reparented if the owner died during startup)."""
    import importlib

    importlib.import_module("ray_tpu.core.worker_main")  # heavy preimport
    try:
        # Workers import jax at startup (worker_main._amain restores the
        # driver's JAX_PLATFORMS); pay its ~0.4 s import once here. The
        # import spawns no threads and initializes no backend, so
        # forking afterwards is safe — backend init happens per-child.
        importlib.import_module("jax")
    except Exception:
        pass
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)  # auto-reap workers
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass
    srv.bind(sock_path + ".tmp")
    os.rename(sock_path + ".tmp", sock_path)  # appearance = ready
    srv.listen(64)
    # Orphan watchdog: a crashed/killed owner (pytest -x abort, kill -9
    # of the head) can never send the shutdown op, and an unsupervised
    # forkserver would outlive its session forever. Poll the owner's
    # liveness between accepts; without an explicit owner, fall back to
    # detecting reparenting.
    parent = os.getppid()
    srv.settimeout(2.0)

    def owner_gone() -> bool:
        if owner_pid is not None:
            # Both launch sites make us a direct child of the owner, so
            # reparenting (even away from a zombie or recycled-pid
            # owner, which kill(pid, 0) cannot distinguish from a live
            # one) means the owner is gone.
            if os.getppid() != owner_pid:
                return True
            try:
                os.kill(owner_pid, 0)
                return False
            except ProcessLookupError:
                return True
            except PermissionError:
                return False
        return os.getppid() != parent

    while True:
        try:
            conn, _ = srv.accept()
        except socket.timeout:
            if owner_gone():
                break
            continue
        try:
            conn.settimeout(30.0)  # don't inherit the 2s accept poll
            f = conn.makefile("rwb")
            line = f.readline()
            if not line:
                continue
            req = json.loads(line)
            if req.get("op") == "shutdown":
                f.write(b'{"ok": true}\n')
                f.flush()
                break
            pid = _spawn_worker(srv, req)
            f.write(json.dumps({"pid": pid}).encode() + b"\n")
            f.flush()
        except Exception as e:  # keep serving on a bad request
            try:
                conn.sendall(json.dumps(
                    {"error": str(e)}).encode() + b"\n")
            except OSError:
                pass
        finally:
            conn.close()
    srv.close()
    try:
        os.unlink(sock_path)
    except FileNotFoundError:
        pass


def _spawn_worker(srv: socket.socket, req: dict) -> int:
    # NOTE for operators: fork() copies argv, so `ps` shows workers
    # under the forkserver's own command line; distinguish them by
    # parent pid (workers are children of the forkserver) or by their
    # RAY_TPU_WORKER_ID environment.
    pid = os.fork()
    if pid != 0:
        return pid
    # -- child: become the worker ---------------------------------------
    try:
        srv.close()
        os.setsid()
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        log_fd = os.open(req["log_path"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        os.close(log_fd)
        os.environ.update(req.get("env") or {})
        from ray_tpu.core import worker_main

        worker_main.main()
        os._exit(0)
    except BaseException:
        import traceback

        traceback.print_exc()
        os._exit(1)
    return 0  # unreachable


class ForkedProc:
    """Popen-like shim for forkserver children (they are the
    forkserver's children, not ours, so no waitpid — liveness via
    signal 0, reaping via the forkserver's SIGCHLD ignore)."""

    def __init__(self, pid: int):
        self.pid = pid
        self._rc: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._rc is not None:
            return self._rc
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self._rc = -1
            return self._rc
        except PermissionError:
            return None
        return None

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def _signal(self, sig) -> None:
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self._rc


class ForkserverClient:
    """Driver-side handle: lazily starts the node's forkserver process
    and requests worker forks over its socket."""

    START_TIMEOUT_S = 60.0

    def __init__(self, session_dir: str, env: Dict[str, str]):
        self.session_dir = session_dir
        self.env = dict(env)
        self.sock_path = os.path.join(
            session_dir, f"forkserver-{os.getpid()}.sock")
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()

    def start_async(self) -> None:
        """Kick the forkserver start on a daemon thread so callers on an
        event loop never block on the ~2.5 s preimport."""
        self._start_failed = False
        threading.Thread(target=self._swallow_start, daemon=True,
                         name="forkserver-start").start()

    def _swallow_start(self) -> None:
        try:
            self.ensure_started()
        except Exception:
            self._start_failed = True  # callers fall back to cold Popen

    def ready(self) -> bool:
        """True when a spawn request would complete in milliseconds."""
        return (self._proc is not None and self._proc.poll() is None
                and os.path.exists(self.sock_path))

    def failed(self) -> bool:
        return getattr(self, "_start_failed", False)

    def ensure_started(self) -> None:
        with self._lock:
            self._ensure_started_locked()

    def _ensure_started_locked(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            return
        log_path = os.path.join(self.session_dir, "logs",
                                "forkserver.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        with open(log_path, "ab") as log_file:
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.forkserver",
                 self.sock_path, str(os.getpid())],
                env=self.env,
                stdout=log_file,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        deadline = time.monotonic() + self.START_TIMEOUT_S
        while not os.path.exists(self.sock_path):
            if self._proc.poll() is not None:
                raise RuntimeError("forkserver died during startup "
                                   f"(see {log_path})")
            if time.monotonic() > deadline:
                raise RuntimeError("forkserver startup timed out")
            time.sleep(0.02)

    def spawn(self, env: Dict[str, str], log_path: str) -> ForkedProc:
        self.ensure_started()
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(30.0)
            s.connect(self.sock_path)
            f = s.makefile("rwb")
            f.write(json.dumps(
                {"env": env, "log_path": log_path}).encode() + b"\n")
            f.flush()
            reply = json.loads(f.readline())
        if "pid" not in reply:
            raise RuntimeError(
                f"forkserver spawn failed: {reply.get('error')}")
        return ForkedProc(reply["pid"])

    def stop(self) -> None:
        if self._proc is None:
            return
        try:
            with socket.socket(socket.AF_UNIX,
                               socket.SOCK_STREAM) as s:
                s.settimeout(5.0)
                s.connect(self.sock_path)
                s.sendall(b'{"op": "shutdown"}\n')
                s.recv(64)
        except OSError:
            pass
        try:
            self._proc.terminate()
            self._proc.wait(timeout=5)
        except Exception:
            try:
                self._proc.kill()
            except Exception:
                pass
        self._proc = None
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass


if __name__ == "__main__":
    serve(sys.argv[1],
          int(sys.argv[2]) if len(sys.argv) > 2 else None)
