"""Worker-log tailer: streams worker stdout/stderr to the driver.

Reference: python/ray/_private/log_monitor.py:103 — a per-node monitor
tails the session's worker log files and publishes new lines; drivers
subscribe and echo them with a worker prefix, so ``print()`` inside a
task shows up at the driver no matter which host ran it.

Here the tailer is embedded in each process that owns worker logs (the
head for its local pool, every node agent for its host) and publishes
over the head's pubsub on the ``worker_logs`` channel.
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

#: Per-poll cap per file — a worker spamming output cannot wedge the
#: control plane (the reference's monitor has the same guard).
MAX_BYTES_PER_POLL = 64 << 10


class LogTailer:
    """Tracks read offsets over a directory of ``worker-*.log`` files
    and returns new complete lines per poll."""

    def __init__(self, logs_dir: str):
        self.logs_dir = logs_dir
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}

    def poll(self) -> List[Tuple[str, List[str]]]:
        """-> [(worker_id_hex_prefix, new_lines)] since the last poll."""
        out: List[Tuple[str, List[str]]] = []
        try:
            names = os.listdir(self.logs_dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("worker-") and name.endswith(".log")):
                continue
            path = os.path.join(self.logs_dir, name)
            worker = name[len("worker-"):-len(".log")]
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(name, 0)
            if size < offset:
                offset = 0  # truncated/rotated: start over
            if size == offset:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(offset)
                    data = f.read(MAX_BYTES_PER_POLL)
            except OSError:
                continue
            self._offsets[name] = offset + len(data)
            data = self._partial.pop(name, b"") + data
            *lines, tail = data.split(b"\n")
            if tail:
                self._partial[name] = tail
            if lines:
                out.append((worker, [
                    ln.decode("utf-8", errors="replace") for ln in lines
                ]))
        return out
