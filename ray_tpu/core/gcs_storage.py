"""Durable backing store for the head's control-plane tables.

Reference mapping: src/ray/gcs/store_client/store_client.h (the pluggable
KV behind gcs_table_storage.h:242) and the redis-backed GCS fault
tolerance story. Here the store is a sqlite file in the session dir:
every mutation is written through synchronously (sqlite WAL), and a
restarted head (same ``--session-dir``) reloads actors, placement
groups, KV, jobs and named-actor bindings before serving.

What survives a head restart:
- internal KV (function table, named refs, user KV),
- detached/named actor records with their creation specs — recreated on
  fresh workers after restart (their old workers died with the head),
- placement-group specs — re-placed once nodes re-register,
- job table (finished-job history).

What intentionally does not: leases, in-flight tasks, object directory
entries (objects died with the node stores; owners recover via lineage).
"""

from __future__ import annotations

import logging
import os
import pickle
import queue
import sqlite3
import threading
from typing import Any, List, Optional, Tuple

logger = logging.getLogger(__name__)


class GcsStorage:
    """Durable control-plane tables.

    Mutations are enqueued to a dedicated writer thread (FIFO, so
    put/delete ordering is preserved) and committed there — the head's
    event loop never blocks on disk. Reads (`get`/`items`) run at boot or
    in tests; they flush the queue first so they observe every enqueued
    write (read-your-writes)."""

    TABLES = ("kv", "actors", "pgs", "jobs")

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self.path = path
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock(  # guards _db across threads
            "gcs_storage.GcsStorage._lock")
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        for table in self.TABLES:
            self._db.execute(
                f"CREATE TABLE IF NOT EXISTS {table} "
                "(k TEXT PRIMARY KEY, v BLOB)")
        self._db.commit()
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        self._writer = threading.Thread(
            target=self._writer_loop, name="gcs-storage", daemon=True)
        self._writer.start()

    # -- generic row ops --------------------------------------------------

    def put(self, table: str, key: str, value: Any) -> None:
        # Pickle on the caller (cheap, and value may mutate later).
        self._queue.put(("put", table, key,
                         pickle.dumps(value, protocol=5)))

    def delete(self, table: str, key: str) -> None:
        self._queue.put(("del", table, key, None))

    def _writer_loop(self):
        while True:
            try:
                # Bounded get (lock-discipline audit): if the close()
                # sentinel is ever lost, the Empty branch notices the
                # closed flag instead of hanging this thread forever.
                op = self._queue.get(timeout=1.0)
            except queue.Empty:
                if self._closed:
                    return
                continue
            if op is None:
                # Balance the join() accounting or a later flush() blocks
                # forever on the never-finished sentinel.
                self._queue.task_done()
                return
            kind, table, key, blob = op
            try:
                with self._lock:
                    if kind == "put":
                        self._db.execute(
                            f"INSERT INTO {table} (k, v) VALUES (?, ?) "
                            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                            (key, blob))
                    else:
                        self._db.execute(
                            f"DELETE FROM {table} WHERE k = ?", (key,))
                    self._db.commit()
            except Exception:
                logger.exception("gcs storage write failed")
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        """Block until every enqueued mutation is committed."""
        self._queue.join()

    def get(self, table: str, key: str) -> Optional[Any]:
        self.flush()
        with self._lock:
            row = self._db.execute(
                f"SELECT v FROM {table} WHERE k = ?", (key,)).fetchone()
        return pickle.loads(row[0]) if row else None

    def items(self, table: str) -> List[Tuple[str, Any]]:
        self.flush()
        with self._lock:
            rows = self._db.execute(f"SELECT k, v FROM {table}").fetchall()
        out = []
        for k, v in rows:
            try:
                out.append((k, pickle.loads(v)))
            except Exception:
                continue  # skip rows written by an incompatible version
        return out

    def close(self) -> None:
        self.flush()
        self._closed = True
        self._queue.put(None)
        self._writer.join(timeout=5)
        with self._lock:
            try:
                self._db.commit()
                self._db.close()
            except Exception:
                pass


def storage_path(session_dir: str) -> str:
    return os.path.join(session_dir, "gcs_state.sqlite")
