"""Standalone head process — run the cluster control plane outside any
driver.

Reference: the forked gcs_server + head raylet of `ray start --head`
(python/ray/scripts/scripts.py start). Drivers attach with
``ray_tpu.init(address="host:port")``; additional machines join with
``python -m ray_tpu.core.node_agent --head-host ... --head-port ...``.

With a pinned ``--port`` and ``--session-dir``, a head killed and
restarted on the same paths recovers its durable state (detached actors,
placement groups, KV, jobs) from the session's sqlite store and
recreates detached actors on fresh workers — the framework's GCS
fault-tolerance story (reference: redis-backed GCS restart +
node_manager.cc:1122 HandleNotifyGCSRestart).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os

logger = logging.getLogger(__name__)


def main():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s head %(name)s: %(message)s",
    )
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=0,
                   help="fixed control-plane port (0 = ephemeral); pin it "
                        "to survive restarts")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--num-cpus", type=float, default=os.cpu_count() or 1)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default=None,
                   help="extra custom resources as JSON")
    p.add_argument("--session-dir", default=None,
                   help="pin to reuse durable state across restarts")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--client-server-port", type=int, default=None,
                   help="also serve thin clients (rtpu:// — the Ray "
                        "Client analog) on this port")
    args = p.parse_args()

    from ray_tpu.core.config import get_config
    from ray_tpu.core.node import HeadNode, detect_node_resources

    config = get_config()
    if args.object_store_memory:
        config.object_store_memory = args.object_store_memory
    resources = detect_node_resources(args.num_cpus, args.num_tpus)
    if args.resources:
        import json

        resources.update({k: float(v)
                          for k, v in json.loads(args.resources).items()})

    node = HeadNode(config, resources, session_dir=args.session_dir,
                    host=args.host, port=args.port)
    print(f"ray_tpu head listening on {args.host}:{node.port} "
          f"(session {node.session_dir})", flush=True)
    # Live profiling plane: a standalone head samples itself too when
    # the continuous mode is configured on.
    from ray_tpu.util import profiler

    profiler.maybe_start_continuous()

    client_srv = None
    if args.client_server_port is not None:
        # Thin-client endpoint (rtpu://): a driver session in THIS
        # process backs it (reference: the proxier runs beside the GCS).
        import ray_tpu
        from ray_tpu.client.server import ClientServer

        ray_tpu.init(address=f"127.0.0.1:{node.port}")
        client_srv = ClientServer(args.host, args.client_server_port)
        print(f"ray_tpu client server on {args.host}:"
              f"{client_srv.start()}", flush=True)

    stop = asyncio.Event()

    async def wait_forever():
        await stop.wait()

    try:
        node.loop_thread.run(wait_forever())
    except KeyboardInterrupt:
        pass
    finally:
        if client_srv is not None:
            client_srv.stop()
        node.shutdown()


if __name__ == "__main__":
    main()
