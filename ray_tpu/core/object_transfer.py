"""Cross-node object transfer: the DCN data plane.

Reference mapping:
- ``ObjectPuller`` ≈ src/ray/object_manager/pull_manager.h:52 — on a local
  store miss, locate holders via the head's object directory, then fetch
  the packed payload in chunks with admission control (bounded in-flight
  bytes) and dedup of concurrent pulls of the same object.
- The serve side ≈ push_manager.h:30 / object_manager.cc chunk reads: any
  process holding the node's store (head or node agent) answers
  ``fetch_object_chunk`` with zero-copy slices of the sealed payload.
- The head's location table ≈ ownership_based_object_directory.h — the
  object directory lives with the GCS in this topology (single control
  plane), populated by ``object_sealed`` reports that carry the sealing
  node's id.

Transport is the framework's length-prefixed msgpack RPC (rpc.py); chunks
ride as msgpack bin payloads over the same connections the control plane
uses, which keeps the implementation transport-agnostic (TCP today,
anything rpc.py learns tomorrow).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import object_store, rpc
from ray_tpu.core.ids import ObjectID

logger = logging.getLogger(__name__)

# 1 MiB chunks: large enough to amortize rpc framing, small enough that a
# handful of concurrent pulls can't head-of-line-block the control plane.
CHUNK_BYTES = 1 << 20
# Admission control: total bytes in flight across all pulls.
MAX_INFLIGHT_BYTES = 64 << 20


def serve_handlers() -> dict:
    """RPC handlers a node-store holder (head / node agent) registers so
    peers can pull sealed objects from this node."""

    async def h_fetch_object_meta(conn, payload):
        object_id = ObjectID.from_hex(payload["object_id"])
        data = object_store.node_store_read_packed(object_id)
        if data is None:
            return {"found": False}
        return {"found": True, "size": len(data)}

    async def h_fetch_object_chunk(conn, payload):
        object_id = ObjectID.from_hex(payload["object_id"])
        data = object_store.node_store_read_packed(object_id)
        if data is None:
            return {"found": False}
        off = int(payload["offset"])
        ln = int(payload["length"])
        # Raw-attachment reply: the chunk is a zero-copy slice of the
        # sealed payload all the way into the transport.
        return rpc.WithAttachment(
            {"found": True, "total": len(data)}, data[off:off + ln])

    return {
        "fetch_object_meta": h_fetch_object_meta,
        "fetch_object_chunk": h_fetch_object_chunk,
    }


class ObjectPuller:
    """Pulls remote sealed objects into the local node store.

    One instance per process. Concurrent pulls of the same object are
    coalesced onto one in-flight future; total in-flight bytes are
    bounded (pull_manager.h admission control).
    """

    def __init__(self, get_connection: Callable[[Tuple[str, int]],
                                                Awaitable]):
        self._get_connection = get_connection
        self._inflight: Dict[str, asyncio.Future] = {}
        self._budget = asyncio.Semaphore(MAX_INFLIGHT_BYTES // CHUNK_BYTES)

    async def pull(self, object_id: ObjectID,
                   locations: List[Tuple[str, int]]) -> bool:
        """Fetch ``object_id`` from one of ``locations`` (fetch-server
        addresses) into the local node store. Returns True on success.
        Safe to call concurrently for the same object."""
        hex_id = object_id.hex()
        fut = self._inflight.get(hex_id)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[hex_id] = fut
        try:
            ok = await self._pull_once(object_id, locations)
            fut.set_result(ok)
            return ok
        except Exception as e:
            fut.set_exception(e)
            # Consume the exception if nobody else awaits this future.
            fut.exception()
            raise
        finally:
            self._inflight.pop(hex_id, None)

    async def _pull_once(self, object_id: ObjectID,
                         locations: List[Tuple[str, int]]) -> bool:
        last_error: Optional[Exception] = None
        for address in locations:
            try:
                if await self._pull_from(object_id, tuple(address)):
                    return True
            except Exception as e:  # holder died mid-pull: try the next
                last_error = e
                logger.debug("pull of %s from %s failed: %s",
                             object_id.hex()[:12], address, e)
        if last_error is not None:
            logger.info("pull of %s failed from all %d holders: %s",
                        object_id.hex()[:12], len(locations), last_error)
        return False

    async def _pull_from(self, object_id: ObjectID,
                         address: Tuple[str, int]) -> bool:
        conn = await self._get_connection(address)
        meta = await conn.call("fetch_object_meta",
                               {"object_id": object_id.hex()})
        if not meta.get("found"):
            return False
        total = meta["size"]
        # Reserve the destination up front and stream chunks INTO it
        # with a windowed in-flight budget (push_manager.h:30): memory
        # stays constant for a multi-GiB object, and chunk requests
        # overlap instead of serializing on one round-trip each.
        writer = object_store.node_store_reserve(object_id, total)
        if writer is object_store.ALREADY_PRESENT:
            return True  # a concurrent pull landed first

        async def fetch(offset: int) -> None:
            ln = min(CHUNK_BYTES, total - offset)
            async with _sem_guard(self._budget):
                reply = await conn.call("fetch_object_chunk", {
                    "object_id": object_id.hex(),
                    "offset": offset, "length": ln,
                })
            if not reply.get("found"):
                raise _PullAborted("holder evicted the object mid-pull")
            chunk = reply.get("__attachment__", b"")
            if len(chunk) != ln:
                raise _PullAborted("truncated chunk")
            writer.write_at(offset, chunk)

        sealed = False
        try:
            results = await asyncio.gather(
                *(fetch(off) for off in range(0, total, CHUNK_BYTES)),
                return_exceptions=True)
            failure = next(
                (r for r in results if isinstance(r, Exception)), None)
            if failure is not None:
                if isinstance(failure, _PullAborted):
                    return False
                raise failure  # connection-level: try next holder
            writer.seal()
            sealed = True
            return True
        finally:
            if not sealed:
                # Covers failures AND cancellation (gather re-raises
                # CancelledError past return_exceptions): a reserved
                # arena slot left unsealed would leak capacity forever.
                writer.abort()


class _PullAborted(Exception):
    """The holder's copy disappeared or shrank mid-pull."""


class _sem_guard:
    def __init__(self, sem: asyncio.Semaphore):
        self._sem = sem

    async def __aenter__(self):
        await self._sem.acquire()

    async def __aexit__(self, *exc):
        self._sem.release()
