"""Cross-node object transfer: the DCN data plane.

Reference mapping:
- ``ObjectPuller`` ≈ src/ray/object_manager/pull_manager.h:52 — on a local
  store miss, locate holders via the head's object directory, then fetch
  the packed payload in chunks with admission control (bounded in-flight
  bytes) and dedup of concurrent pulls of the same object.
- The serve side ≈ push_manager.h:30 / object_manager.cc chunk reads: any
  process holding the node's store (head or node agent) answers
  ``fetch_object_chunk`` with zero-copy slices of the sealed payload.
- The head's location table ≈ ownership_based_object_directory.h — the
  object directory lives with the GCS in this topology (single control
  plane), populated by ``object_sealed`` reports that carry the sealing
  node's id.

Transport is the framework's length-prefixed msgpack RPC (rpc.py); chunks
ride as msgpack bin payloads over the same connections the control plane
uses, which keeps the implementation transport-agnostic (TCP today,
anything rpc.py learns tomorrow).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import struct
import threading
import time
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import object_store, retry, rpc
from ray_tpu.core.ids import ObjectID

logger = logging.getLogger(__name__)

# 1 MiB chunks: large enough to amortize rpc framing, small enough that a
# handful of concurrent pulls can't head-of-line-block the control plane.
CHUNK_BYTES = 1 << 20
# Admission control: total bytes in flight across all pulls.
MAX_INFLIGHT_BYTES = 64 << 20

# ---------------------------------------------------------------------------
# Bulk data plane (reference: the object manager's dedicated transfer
# connections vs the gRPC control plane — object_manager.h:117). Framing a
# GiB through the asyncio control transport costs ~4 user-space copies per
# byte (slice → transport buffer → StreamReader → chunk bytes → store);
# this plane is plain blocking sockets on their own threads: the holder
# sendall()s zero-copy views of the sealed payload and the puller
# recv_into()s straight into the reserved arena slot — one user→kernel and
# one kernel→user copy per byte, GIL released throughout.
# ---------------------------------------------------------------------------

_DATA_REQ = struct.Struct("<I Q Q")  # id length, offset, length
_DATA_MISSING = 0xFFFFFFFFFFFFFFFF
_RECV_CAP = 4 << 20  # per-recv_into cap; also the socket buffer size


class DataPlaneServer:
    """Per-holder listener answering range reads of sealed objects.
    Binds per the process's bind policy (RAY_TPU_BIND_HOST, set by the
    node agent / head for loopback-only deployments) — the protocol is
    unauthenticated, so it must not silently widen the configured
    exposure."""

    def __init__(self, host: Optional[str] = None, port: int = 0):
        if host is None:
            import os

            # Fail-safe default is loopback: only deployments that
            # configured a wider control-plane exposure (node agent /
            # head set RAY_TPU_BIND_HOST) widen the data plane.
            host = os.environ.get("RAY_TPU_BIND_HOST", "127.0.0.1")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._closed = False
        threading.Thread(target=self._accept_loop,
                         name="rtpu-dataplane", daemon=True).start()

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                _RECV_CAP)
            except OSError:
                pass
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while True:
                head = _recv_exactly(conn, _DATA_REQ.size)
                if head is None:
                    return
                idlen, offset, length = _DATA_REQ.unpack(head)
                if idlen > 64:
                    return  # protocol violation
                raw_id = _recv_exactly(conn, idlen)
                if raw_id is None:
                    return
                try:
                    object_id = ObjectID(bytes(raw_id))
                    view = object_store.node_store_read_packed(object_id)
                except Exception:
                    view = None
                if view is None:
                    # Device-plane shard ids ride the same range-read
                    # protocol: the holder serves a host view of one
                    # shard (core/device_objects.py registry).
                    try:
                        from ray_tpu.core import device_objects

                        view = device_objects.shard_view(bytes(raw_id))
                    except Exception:
                        view = None
                if view is None or offset > len(view):
                    conn.sendall((_DATA_MISSING).to_bytes(8, "little"))
                    continue
                payload = memoryview(view)[offset:offset + length]
                conn.sendall(len(payload).to_bytes(8, "little"))
                if payload.nbytes:
                    conn.sendall(payload)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


_data_server: Optional[DataPlaneServer] = None

# Same-host holder arenas this process has attached (name -> arena).
# Guarded by _peer_arenas_lock (copies run on executor threads). Growth
# is bounded by the number of distinct holder daemon INSTANCES this
# process ever pulled from on its own host; mappings persist for the
# process lifetime (cheap: address space, shared pages).
_peer_arenas: Dict[str, object] = {}
_peer_arenas_lock = threading.Lock()
_local_hosts_cache: Optional[set] = None


def _is_local_host(host: str) -> bool:
    global _local_hosts_cache
    if _local_hosts_cache is None:
        hosts = {"127.0.0.1", "localhost", "::1", "0.0.0.0", ""}
        try:
            name = socket.gethostname()
            hosts.add(name)
            hosts.update(info[4][0]
                         for info in socket.getaddrinfo(name, None))
        except OSError:
            pass
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect(("8.8.8.8", 80))
                hosts.add(s.getsockname()[0])
            finally:
                s.close()
        except OSError:
            pass
        _local_hosts_cache = hosts
    return host in _local_hosts_cache


def _copy_from_peer_arena(arena_name: str, object_id: ObjectID,
                          dest: memoryview, total: int) -> bool:
    """(worker thread) Same-host fast path: attach the holder's shm
    arena and memcpy the sealed payload straight into our reserved
    slot — no sockets at all (reference: plasma same-node sharing).
    The lookup takes a read pin, so a concurrent delete on the holder
    defers the free past the copy."""
    from ray_tpu.core import native_store

    with _peer_arenas_lock:
        arena = _peer_arenas.get(arena_name)
    if arena is None:
        arena = native_store.NativeArena.attach(arena_name)
        if arena is None:
            return False
        with _peer_arenas_lock:
            arena = _peer_arenas.setdefault(arena_name, arena)
    view = arena.lookup(object_id.binary())
    if view is None or len(view) < total:
        return False
    src = view[:total]
    # Batch-fault the freshly-attached source range: lazy read faults
    # per 4KiB would dominate the copy on virtualized hosts.
    object_store.populate_range(src, object_store.MADV_POPULATE_READ)
    dest[:total] = src
    return True


def ensure_data_server() -> int:
    """Start (once) this process's data-plane listener; returns port."""
    global _data_server
    if _data_server is None:
        _data_server = DataPlaneServer()
    return _data_server.port


def _recv_exactly(conn: socket.socket, n: int) -> Optional[bytearray]:
    buf = bytearray(n)
    mv = memoryview(buf)
    got = 0
    while got < n:
        r = conn.recv_into(mv[got:], n - got)
        if r == 0:
            return None
        got += r
    return buf


# Idle data-plane connections, pooled per holder address (the protocol
# is request/response on one stream, so a cleanly-drained connection is
# reusable — paying a TCP connect + thread spawn per pulled object adds
# up at steady state). A pooled connection whose holder restarted
# raises on reuse; the caller's data-plane failure path falls back to
# chunked rpc, so staleness degrades, never wedges.
_data_conns: Dict[Tuple[str, int], list] = {}
_data_conns_lock = threading.Lock()


def _borrow_data_conn(address: Tuple[str, int]) -> socket.socket:
    with _data_conns_lock:
        pool = _data_conns.get(address)
        if pool:
            return pool.pop()
    conn = socket.create_connection(address, timeout=120)
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, _RECV_CAP)
    except OSError:
        pass
    return conn


def _return_data_conn(address: Tuple[str, int], conn: socket.socket):
    with _data_conns_lock:
        _data_conns.setdefault(address, []).append(conn)


def _pull_range_direct(address: Tuple[str, int], object_id: ObjectID,
                       dest: memoryview, offset: int, length: int,
                       state: Optional[dict] = None):
    """(worker thread) Stream [offset, offset+length) of the packed
    payload straight into ``dest`` (a slice of the reserved store
    slot). Raises on any shortfall. ``state["stop"]`` (set when the
    awaiting pull is cancelled) aborts between recvs."""
    conn = _borrow_data_conn(address)
    clean = False
    try:
        raw = object_id.binary()
        conn.sendall(_DATA_REQ.pack(len(raw), offset, length) + raw)
        head = _recv_exactly(conn, 8)
        if head is None:
            raise _PullAborted("data plane connection closed")
        avail = int.from_bytes(head, "little")
        if avail == _DATA_MISSING or avail != length:
            raise _PullAborted(
                f"holder served {avail} of {length} requested bytes")
        got = 0
        while got < length:
            if state is not None and state.get("stop"):
                raise _PullAborted("pull cancelled")
            r = conn.recv_into(dest[got:],
                               min(length - got, _RECV_CAP))
            if r == 0:
                raise _PullAborted("data plane EOF mid-payload")
            got += r
        clean = True  # stream fully drained: reusable
    finally:
        if clean:
            _return_data_conn(address, conn)
        else:
            try:
                conn.close()  # unknown stream state: never pool it
            except OSError:
                pass


def serve_handlers() -> dict:
    """RPC handlers a node-store holder (head / node agent) registers so
    peers can pull sealed objects from this node."""

    async def h_fetch_object_meta(conn, payload):
        object_id = ObjectID.from_hex(payload["object_id"])
        data = object_store.node_store_read_packed(object_id)
        if data is None:
            return {"found": False}
        return {"found": True, "size": len(data),
                "data_port": ensure_data_server(),
                "arena": object_store.node_store_arena_name(object_id)}

    async def h_fetch_object_chunk(conn, payload):
        object_id = ObjectID.from_hex(payload["object_id"])
        data = object_store.node_store_read_packed(object_id)
        if data is None:
            return {"found": False}
        off = int(payload["offset"])
        ln = int(payload["length"])
        # Raw-attachment reply: the chunk is a zero-copy slice of the
        # sealed payload all the way into the transport.
        return rpc.WithAttachment(
            {"found": True, "total": len(data)}, data[off:off + ln])

    return {
        "fetch_object_meta": h_fetch_object_meta,
        "fetch_object_chunk": h_fetch_object_chunk,
    }


class ObjectPuller:
    """Pulls remote sealed objects into the local node store.

    One instance per process. Concurrent pulls of the same object are
    coalesced onto one in-flight future; total in-flight bytes are
    bounded (pull_manager.h admission control).
    """

    def __init__(self, get_connection: Callable[[Tuple[str, int]],
                                                Awaitable],
                 policy: Optional[retry.RetryPolicy] = None):
        self._get_connection = get_connection
        self._inflight: Dict[str, asyncio.Future] = {}
        self._budget = asyncio.Semaphore(MAX_INFLIGHT_BYTES // CHUNK_BYTES)
        self._retry = policy

    def _policy(self) -> retry.RetryPolicy:
        if self._retry is None:
            from ray_tpu.core.config import get_config

            cfg = get_config()
            self._retry = retry.RetryPolicy.from_config(
                cfg, max_attempts=max(1, cfg.object_pull_max_attempts))
        return self._retry

    async def pull(self, object_id: ObjectID,
                   locations: List[Tuple[str, int]]) -> bool:
        """Fetch ``object_id`` from one of ``locations`` (fetch-server
        addresses) into the local node store. Returns True on success.
        Safe to call concurrently for the same object."""
        hex_id = object_id.hex()
        fut = self._inflight.get(hex_id)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[hex_id] = fut
        from ray_tpu.util import telemetry

        t_wall = time.time()
        t0 = time.perf_counter()
        status = "error"
        try:
            ok = await self._pull_once(object_id, locations)
            status = "ok" if ok else "miss"
            fut.set_result(ok)
            return ok
        except Exception as e:
            fut.set_exception(e)
            # Consume the exception if nobody else awaits this future.
            fut.exception()
            raise
        finally:
            self._inflight.pop(hex_id, None)
            elapsed = time.perf_counter() - t0
            telemetry.observe("ray_tpu_object_pull_seconds", elapsed,
                              {"status": status})
            telemetry.event("objects", f"pull {hex_id[:8]}", ts=t_wall,
                            dur=elapsed, args={"status": status})
            from ray_tpu.util import flight_recorder

            flight_recorder.record(
                "object", "pulled",
                severity="info" if status == "ok" else "warn",
                object=hex_id[:16], status=status,
                dur_s=round(elapsed, 4))

    async def _pull_once(self, object_id: ObjectID,
                         locations: List[Tuple[str, int]]) -> bool:
        """Sweep the holder list; retry the whole sweep under the
        unified policy so a transient drop/partition to every holder
        heals instead of surfacing as object loss."""
        if not locations:
            return False
        last_error: Optional[Exception] = None
        policy = self._policy()
        for delay in policy.backoff_series():
            if delay:
                policy.total_retries += 1
                await asyncio.sleep(delay)
            sweep_error: Optional[Exception] = None
            for address in locations:
                try:
                    if await self._pull_from(object_id, tuple(address)):
                        return True
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # holder died mid-pull: try next
                    sweep_error = e
                    logger.debug("pull of %s from %s failed: %s",
                                 object_id.hex()[:12], address, e)
            if sweep_error is None:
                # Every holder answered cleanly "not present": nothing
                # transient to heal — fail fast into reconstruction
                # instead of burning backoff on redundant sweeps.
                break
            last_error = sweep_error
        if last_error is not None:
            logger.info("pull of %s failed from all %d holders: %s",
                        object_id.hex()[:12], len(locations), last_error)
        return False

    async def _pull_from(self, object_id: ObjectID,
                         address: Tuple[str, int]) -> bool:
        conn = await self._get_connection(address)
        meta = await conn.call("fetch_object_meta",
                               {"object_id": object_id.hex()})
        if not meta.get("found"):
            return False
        total = meta["size"]
        # Reserve the destination up front and stream chunks INTO it
        # with a windowed in-flight budget (push_manager.h:30): memory
        # stays constant for a multi-GiB object, and chunk requests
        # overlap instead of serializing on one round-trip each.
        writer = object_store.node_store_reserve(object_id, total)
        if writer is object_store.ALREADY_PRESENT:
            return True  # a concurrent pull landed first
        # Fast paths: same-host arena memcpy, then the bulk data plane
        # (two kernel copies total; no rpc framing). Chunked rpc over
        # the control connection is the last resort (no direct view —
        # shm-segment/spill destinations — or the data port
        # unreachable, e.g. firewalled to the configured ports only).
        direct = writer.direct_view()
        t_path = time.perf_counter()
        if direct is not None and total > 0:
            holder_arena = meta.get("arena")
            if holder_arena and _is_local_host(address[0]):
                outcome = await self._run_settled(
                    writer,
                    lambda state: _copy_from_peer_arena(
                        holder_arena, object_id, direct, total))
                logger.debug("pull path=peer-arena %s %.0fMiB in %.2fs",
                             outcome, total / (1 << 20),
                             time.perf_counter() - t_path)
                if outcome is True:
                    return True
                # Holder's copy vanished from its arena mid-flight (or
                # the attach failed): reserve anew, try the sockets.
                writer = object_store.node_store_reserve(object_id,
                                                         total)
                if writer is object_store.ALREADY_PRESENT:
                    return True
                direct = writer.direct_view()
            data_port = meta.get("data_port")
            if direct is not None and data_port:
                try:
                    await self._pull_direct(
                        object_id, (address[0], data_port), writer,
                        direct, total)
                    logger.debug("pull path=data-plane %.0fMiB in %.2fs",
                                 total / (1 << 20),
                                 time.perf_counter() - t_path)
                    return True
                except _PullAborted:
                    return False
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # data port unreachable etc.
                    logger.info("data-plane pull from %s failed (%s); "
                                "falling back to chunked rpc",
                                address[0], e)
                writer = object_store.node_store_reserve(object_id,
                                                         total)
                if writer is object_store.ALREADY_PRESENT:
                    return True

        return await self._pull_chunked(object_id, conn, writer, total)

    @staticmethod
    async def _run_settled(writer, fn):
        """Run ``fn(state)`` on the executor with the WRITER's fate
        owned by a done-callback: seal on success, abort on failure —
        and crucially only AFTER the thread stopped touching the
        reserved slot. A cancellation of this coroutine must neither
        leak the reservation nor free the slot while an orphaned
        thread still writes into it (the memory would be reused by the
        next allocation and silently corrupted). Returns True/False,
        or raises the thread's exception."""
        loop = asyncio.get_running_loop()
        state = {"stop": False}
        job = loop.run_in_executor(None, fn, state)
        done = loop.create_future()

        def settle(fut):
            try:
                ok = fut.result()
            except BaseException as e:  # noqa: BLE001
                writer.abort()
                outcome = e
            else:
                if ok:
                    # Complete even if the awaiter was cancelled: the
                    # copy finished, the object is whole — sealing is
                    # free.
                    writer.seal()
                else:
                    writer.abort()
                outcome = bool(ok)
            if not done.done():
                done.set_result(outcome)

        job.add_done_callback(settle)
        try:
            outcome = await asyncio.shield(done)
        except asyncio.CancelledError:
            state["stop"] = True  # threads drain; settle() cleans up
            raise
        if isinstance(outcome, BaseException):
            raise outcome
        return outcome

    #: Objects above this split across parallel data-plane streams
    #: (parallel TCP + NIC queues on real DCN; the GIL is released in
    #: the socket syscalls so stripes genuinely overlap).
    STRIPE_THRESHOLD = 64 << 20
    STRIPES = 2

    async def _pull_direct(self, object_id: ObjectID,
                           address: Tuple[str, int], writer,
                           dest: memoryview, total: int) -> None:
        """Stream over the data plane into the reserved slot. Writer
        fate (seal/abort) is settled only once every stripe thread has
        stopped writing — see _run_settled for why cancellation must
        not abort a slot that threads still touch."""
        loop = asyncio.get_running_loop()
        state = {"stop": False}
        stripes = self.STRIPES if total >= self.STRIPE_THRESHOLD else 1
        bounds = [total * i // stripes for i in range(stripes + 1)]
        jobs = []
        for lo, hi in zip(bounds, bounds[1:]):
            if hi > lo:
                jobs.append(loop.run_in_executor(
                    None, _pull_range_direct, address, object_id,
                    dest[lo:hi], lo, hi - lo, state))
        agg = asyncio.gather(*jobs, return_exceptions=True)
        done = loop.create_future()

        def settle(fut):
            results = fut.result()  # list (gather had return_exceptions)
            failure = next((r for r in results
                            if isinstance(r, BaseException)), None)
            if failure is None:
                writer.seal()
            else:
                writer.abort()
            if not done.done():
                done.set_result(failure)

        agg.add_done_callback(settle)
        try:
            failure = await asyncio.shield(done)
        except asyncio.CancelledError:
            state["stop"] = True
            raise
        if failure is not None:
            raise failure

    async def _pull_chunked(self, object_id: ObjectID, conn,
                            writer, total: int) -> bool:
        async def fetch(offset: int) -> None:
            ln = min(CHUNK_BYTES, total - offset)
            async with _sem_guard(self._budget):
                reply = await conn.call("fetch_object_chunk", {
                    "object_id": object_id.hex(),
                    "offset": offset, "length": ln,
                })
            if not reply.get("found"):
                raise _PullAborted("holder evicted the object mid-pull")
            chunk = reply.get("__attachment__", b"")
            if len(chunk) != ln:
                raise _PullAborted("truncated chunk")
            writer.write_at(offset, chunk)

        sealed = False
        try:
            results = await asyncio.gather(
                *(fetch(off) for off in range(0, total, CHUNK_BYTES)),
                return_exceptions=True)
            failure = next(
                (r for r in results if isinstance(r, Exception)), None)
            if failure is not None:
                if isinstance(failure, _PullAborted):
                    return False
                raise failure  # connection-level: try next holder
            writer.seal()
            sealed = True
            return True
        finally:
            if not sealed:
                # Covers failures AND cancellation (gather re-raises
                # CancelledError past return_exceptions): a reserved
                # arena slot left unsealed would leak capacity forever.
                writer.abort()


class _PullAborted(Exception):
    """The holder's copy disappeared or shrank mid-pull."""


# ---------------------------------------------------------------------------
# device-plane per-shard pulls (core/device_objects.py consumers)
# ---------------------------------------------------------------------------

#: Chunk size for the rpc fallback of shard pulls (same sizing rationale
#: as CHUNK_BYTES: amortize framing, don't head-of-line-block control).
SHARD_CHUNK_BYTES = CHUNK_BYTES


def pull_shard_into(address: Tuple[str, int], shard_id_bytes: bytes,
                    dest: memoryview, state: Optional[dict] = None,
                    max_resumes: int = 3) -> None:
    """(worker thread) Resumable range-read of one device shard over the
    bulk data plane, straight into ``dest``.

    Bytes that already landed are never re-transferred: a mid-stream
    connection drop resumes at the received offset with a fresh range
    request, up to ``max_resumes`` times. Raises _PullAborted when the
    holder no longer serves the shard; OSError bubbles a dead data port
    so the caller can fall back to chunked rpc."""
    total = dest.nbytes
    got = 0
    resumes = 0
    while got < total:
        if state is not None and state.get("stop"):
            raise _PullAborted("shard pull cancelled")
        conn = _borrow_data_conn(address)
        clean = False
        try:
            conn.sendall(
                _DATA_REQ.pack(len(shard_id_bytes), got, total - got)
                + shard_id_bytes)
            head = _recv_exactly(conn, 8)
            if head is None:
                raise OSError("data plane connection closed")
            avail = int.from_bytes(head, "little")
            if avail == _DATA_MISSING:
                raise _PullAborted("holder no longer serves the shard")
            if avail != total - got:
                raise _PullAborted(
                    f"holder served {avail} of {total - got} shard bytes")
            while got < total:
                if state is not None and state.get("stop"):
                    raise _PullAborted("shard pull cancelled")
                r = conn.recv_into(dest[got:],
                                   min(total - got, _RECV_CAP))
                if r == 0:
                    raise OSError("data plane EOF mid-shard")
                got += r
            clean = True
        except OSError:
            resumes += 1
            if resumes > max_resumes:
                raise
            # Resume from `got`: the landed prefix stays.
        finally:
            if clean:
                _return_data_conn(address, conn)
            else:
                try:
                    conn.close()
                except OSError:  # lint: allow-silent(close of an already-failed data conn)
                    pass


class _sem_guard:
    def __init__(self, sem: asyncio.Semaphore):
        self._sem = sem

    async def __aenter__(self):
        await self._sem.acquire()

    async def __aexit__(self, *exc):
        self._sem.release()
