"""Unified retry/backoff policy for the RPC stack.

Reference: the reference runtime scatters retry loops across the GCS
client (gcs_rpc_client.h retryable-grpc-client), the core worker's task
resubmission, the object manager's pull retries and Serve's router.
This module centralizes the policy so every retry site shares one
envelope — exponential backoff with jitter, max-attempts, an overall
deadline — and one safety rule: a ``ConnectionLost`` whose ``sent``
flag is True is only retried when the caller declares the operation
idempotent (at-most-once semantics for everything else).

Consumers:
- ``core_worker``: task/actor push frames, function-table polls,
  death-reason probes, object-recovery probes.
- ``gcs``/``node_agent``: agent-side spawn pushes; the agent's
  reconnect-with-backoff to the head after a dropped health channel.
- ``object_transfer``: pull sweeps across holders.
- ``serve.router``: request assignment, plus the per-replica
  ``CircuitBreaker`` that sheds traffic from broken replicas.
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, Iterator, Optional, Tuple, Type

from ray_tpu.core import rpc

logger = logging.getLogger(__name__)


def _metric_site(label: str) -> str:
    """Bounded-cardinality tag from a free-form retry label: drop
    tokens that look like ids (hex suffixes, deployment keys, digits)
    so per-call-site labels don't explode the tag space."""
    words = []
    for w in (label or "").split():
        if "#" in w or any(c.isdigit() for c in w):
            continue
        if len(w) >= 10 and all(c in "0123456789abcdef" for c in w):
            continue
        words.append(w)
    return " ".join(words) or "unlabeled"


def _record_retry(label: str, delay: float,
                  error: Optional[BaseException]) -> None:
    from ray_tpu.util import flight_recorder, telemetry

    site = _metric_site(label)
    telemetry.inc("ray_tpu_retries_total", 1, {"site": site})
    telemetry.inc("ray_tpu_retry_backoff_seconds_total", delay,
                  {"site": site})
    telemetry.event("retry", f"retry {label or site}", dur=delay,
                    args={"error": (type(error).__name__ if error
                                    else "predicate_false")})
    flight_recorder.record(
        "rpc", "retry", severity="warn", site=label or site,
        backoff_s=round(delay, 4),
        error=(type(error).__name__ if error else "predicate_false"))


def _record_deadline_exhausted(label: str) -> None:
    from ray_tpu.util import flight_recorder, telemetry

    telemetry.inc("ray_tpu_retry_deadline_exhausted_total", 1,
                  {"site": _metric_site(label)})
    flight_recorder.record("rpc", "deadline_exhausted",
                           severity="error", site=label or "unlabeled")

# Transport-level failures: the request may never have reached (or never
# have left) the peer. Plain RpcError is deliberately excluded — it
# carries a remote handler's exception, which is deterministic and must
# not be replayed.
TRANSIENT_ERRORS: Tuple[Type[BaseException], ...] = (
    rpc.ConnectionLost,
    asyncio.TimeoutError,
    TimeoutError,
    OSError,
)


class PollTimeout(Exception):
    """RetryPolicy.poll exhausted its deadline without the predicate
    ever holding. ``last_result``/``last_error`` carry the final poll's
    outcome so the call site can raise a domain-specific error."""

    def __init__(self, msg: str = "", last_result: Any = None,
                 last_error: Optional[BaseException] = None):
        super().__init__(msg)
        self.last_result = last_result
        self.last_error = last_error


@dataclass
class RetryPolicy:
    """Exponential backoff with jitter, bounded attempts, optional
    overall deadline.

    One instance is typically shared per process/subsystem; the
    ``total_attempts``/``total_retries`` counters make retry behavior
    observable to tests and metrics without extra plumbing.
    """

    max_attempts: int = 5
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    #: Fractional jitter: each delay is scaled by a uniform factor in
    #: [1 - jitter, 1 + jitter]. 0 disables (deterministic backoff).
    jitter: float = 0.5
    #: Exception classes considered transient. See TRANSIENT_ERRORS.
    retry_on: Tuple[Type[BaseException], ...] = TRANSIENT_ERRORS
    #: Seed for the jitter RNG (deterministic tests).
    seed: Optional[int] = None

    total_attempts: int = field(default=0, compare=False)
    total_retries: int = field(default=0, compare=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    @classmethod
    def from_config(cls, config, **overrides) -> "RetryPolicy":
        """Build from the ``rpc_retry_*`` knobs in core/config.py (each
        overridable with a ``RAY_TPU_RPC_RETRY_*`` env var)."""
        kw = dict(
            max_attempts=config.rpc_retry_max_attempts,
            base_delay_s=config.rpc_retry_base_delay_s,
            max_delay_s=config.rpc_retry_max_delay_s,
            multiplier=config.rpc_retry_multiplier,
            jitter=config.rpc_retry_jitter,
        )
        kw.update(overrides)
        return cls(**kw)

    # -- delay schedule -------------------------------------------------

    def backoff_delay(self, retry_index: int) -> float:
        """Delay before retry ``retry_index`` (0-based)."""
        delay = min(self.base_delay_s * (self.multiplier ** retry_index),
                    self.max_delay_s)
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, delay)

    def backoff_series(self, n: Optional[int] = None) -> Iterator[float]:
        """Yield ``n`` (default: max_attempts) delays starting with 0.0
        — drop-in replacement for hand-rolled ``for delay in (0.0, 0.3,
        1.0)`` probe loops."""
        count = self.max_attempts if n is None else n
        for i in range(count):
            yield 0.0 if i == 0 else self.backoff_delay(i - 1)

    # -- retryability ---------------------------------------------------

    def is_transient(self, error: BaseException, idempotent: bool = True
                     ) -> bool:
        """True when ``error`` may be retried. A ``ConnectionLost`` with
        ``sent=True`` means the peer may have executed the request; it
        is retried only for idempotent operations (at-most-once for the
        rest). ``sent=False`` is always a free retry — the frame never
        hit the socket."""
        if not isinstance(error, self.retry_on):
            return False
        if isinstance(error, rpc.ConnectionLost):
            return idempotent or not error.sent
        # Other transients (timeouts, resets) are ambiguous about
        # whether the peer executed the request: idempotent-only.
        return idempotent

    # -- execution ------------------------------------------------------

    def _retry_delay(self, error: BaseException, retry_index: int,
                     idempotent: bool,
                     should_retry: Optional[Callable[[BaseException], bool]],
                     deadline: Optional[float], label: str
                     ) -> Optional[float]:
        """The one retry decision, shared by the async and sync drivers:
        returns the backoff delay for the next attempt, or None when the
        policy is exhausted / the error must propagate."""
        if retry_index + 1 >= self.max_attempts:
            return None
        if not self.is_transient(error, idempotent):
            return None
        if should_retry is not None and not should_retry(error):
            return None
        delay = self.backoff_delay(retry_index)
        if deadline is not None and time.monotonic() + delay >= deadline:
            _record_deadline_exhausted(label)
            return None
        self.total_retries += 1
        _record_retry(label, delay, error)
        logger.debug("retry %d/%d%s after %s: backoff %.3fs",
                     retry_index + 1, self.max_attempts - 1,
                     f" ({label})" if label else "",
                     type(error).__name__, delay)
        return delay

    async def execute(self, fn: Callable[[], Awaitable[Any]], *,
                      idempotent: bool = True,
                      deadline_s: Optional[float] = None,
                      timeout_per_attempt: Optional[float] = None,
                      should_retry: Optional[Callable[[BaseException], bool]] = None,
                      label: str = "") -> Any:
        """Run ``await fn()`` under the policy.

        ``deadline_s`` is an overall wall budget: it caps each attempt's
        timeout AND stops retrying once the budget (minus the pending
        backoff sleep) is spent — deadline propagation, not per-attempt
        reset. ``should_retry`` is an extra caller veto evaluated after
        the transient check (e.g. "only retry while the connection is
        still open")."""
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        retry_index = 0
        while True:
            self.total_attempts += 1
            try:
                timeout = timeout_per_attempt
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        _record_deadline_exhausted(label)
                        raise asyncio.TimeoutError(
                            f"deadline exhausted before attempt ({label})")
                    timeout = (remaining if timeout is None
                               else min(timeout, remaining))
                if timeout is not None:
                    return await asyncio.wait_for(fn(), timeout)
                return await fn()
            except BaseException as e:  # noqa: BLE001 — filtered below
                delay = self._retry_delay(e, retry_index, idempotent,
                                          should_retry, deadline, label)
                if delay is None:
                    raise
                retry_index += 1
                await asyncio.sleep(delay)

    def execute_sync(self, fn: Callable[[], Any], *,
                     idempotent: bool = True,
                     deadline_s: Optional[float] = None,
                     should_retry: Optional[Callable[[BaseException], bool]] = None,
                     label: str = "") -> Any:
        """Blocking-thread variant of ``execute`` (Serve router / other
        non-asyncio callers)."""
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        retry_index = 0
        while True:
            self.total_attempts += 1
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — filtered below
                delay = self._retry_delay(e, retry_index, idempotent,
                                          should_retry, deadline, label)
                if delay is None:
                    raise
                retry_index += 1
                time.sleep(delay)

    async def poll(self, fn: Callable[[], Awaitable[Any]], *,
                   predicate: Callable[[Any], bool] = bool,
                   deadline_s: float,
                   label: str = "") -> Any:
        """Re-run ``fn`` until ``predicate(result)`` holds, sleeping the
        policy's backoff between polls (attempts unbounded; the deadline
        is the budget, and also bounds each in-flight await — a dropped
        reply cannot hang the poll past it). Transient errors count as a
        failed poll; other errors propagate. Raises ``PollTimeout`` at
        the deadline."""
        deadline = time.monotonic() + deadline_s
        retry_index = 0
        last_result: Any = None
        last_error: Optional[BaseException] = None

        def timed_out():
            _record_deadline_exhausted(label)
            return PollTimeout(
                f"poll{f' ({label})' if label else ''} deadline "
                f"({deadline_s:.1f}s) exhausted",
                last_result=last_result, last_error=last_error)

        while True:
            self.total_attempts += 1
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise timed_out()
            try:
                last_result = await asyncio.wait_for(fn(), remaining)
                last_error = None
                if predicate(last_result):
                    return last_result
            except BaseException as e:  # noqa: BLE001 — filtered below
                if not self.is_transient(e, True):
                    raise
                last_error = e
            delay = self.backoff_delay(retry_index)
            retry_index += 1
            self.total_retries += 1
            if time.monotonic() + delay >= deadline:
                raise timed_out()
            _record_retry(label, delay, last_error)
            await asyncio.sleep(delay)


class CircuitBreaker:
    """Per-key consecutive-failure breaker (Serve replicas, peers).

    CLOSED: traffic flows. After ``failure_threshold`` consecutive
    failures the key OPENs for ``reset_timeout_s`` — ``available``
    returns False so routers shed to healthy keys. Once the window
    elapses the key is HALF_OPEN: available again, and the next outcome
    decides (success closes, failure re-opens for a fresh window).
    Thread-safe: Serve's router is driven from arbitrary user threads.
    """

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock("retry.CircuitBreaker._lock")
        # key -> [consecutive_failures, open_until (0 when closed)]
        self._entries: Dict[str, list] = {}

    def record_success(self, key: str) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
        if entry is not None and entry[1]:
            # A previously tripped key recovering (half-open probe
            # success) is a CLOSED transition worth observing.
            from ray_tpu.util import flight_recorder, telemetry

            telemetry.inc("ray_tpu_circuit_breaker_transitions_total", 1,
                          {"state": "closed"})
            telemetry.event("breaker", f"{key} closed")
            flight_recorder.record("rpc", "breaker_closed", key=key)

    def record_failure(self, key: str) -> None:
        opened = False
        with self._lock:
            entry = self._entries.setdefault(key, [0, 0.0])
            now = self._clock()
            was_open = now < entry[1]
            entry[0] += 1
            if entry[0] >= self.failure_threshold:
                entry[1] = now + self.reset_timeout_s
                # Half-open probe failure re-opens with a fresh count.
                entry[0] = self.failure_threshold - 1
                # A failure while ALREADY open extends the window but is
                # not a new transition — one trip, one count.
                opened = not was_open
        if opened:
            from ray_tpu.util import flight_recorder, telemetry

            telemetry.inc("ray_tpu_circuit_breaker_transitions_total", 1,
                          {"state": "open"})
            telemetry.event("breaker", f"{key} open")
            flight_recorder.record("rpc", "breaker_open", severity="warn",
                                   key=key)

    def available(self, key: str) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return True
            return self._clock() >= entry[1]

    def state(self, key: str) -> str:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return "CLOSED"
            if self._clock() < entry[1]:
                return "OPEN"
            return "HALF_OPEN" if entry[1] else "CLOSED"

    def forget(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def retain(self, keys) -> None:
        """Drop every entry NOT in ``keys`` — callers sync the breaker
        to a live-replica set so entries can't leak across churn."""
        keys = set(keys)
        with self._lock:
            for key in list(self._entries):
                if key not in keys:
                    del self._entries[key]
