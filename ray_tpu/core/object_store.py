"""Two-tier object storage.

Tier 1 — ``MemoryStore``: per-process in-memory store for small objects and
direct-call returns (reference: src/ray/core_worker/store_provider/
memory_store/memory_store.h:43 CoreWorkerMemoryStore). Supports blocking and
async waiters.

Tier 2 — ``ShmStore``: node-wide shared-memory store for large objects
(reference: the plasma store, src/ray/object_manager/plasma/store.h:55).
Objects live in named POSIX shared-memory segments (/dev/shm), are written
once and sealed (immutable), and are mapped zero-copy by any process on the
node. Eviction is LRU over unpinned sealed objects
(reference: plasma/eviction_policy.h:105).

An object's segment name is derived from its ID, so any process on the node
can open it without a directory lookup; existence/seal coordination is done
through the control plane (object directory in the GCS-equivalent).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from multiprocessing import shared_memory
from typing import Callable, Dict, List, Optional

import msgpack

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject
from ray_tpu.exceptions import ObjectStoreFullError

_ALIGN = 64


def _report_store_usage(used_bytes: int, num_objects: int) -> None:
    """Node-store gauges, tagged by node: every process on a node
    reports the same authoritative accounting, so last-write-wins per
    node tag yields the true per-node (and summable cluster) totals."""
    from ray_tpu.util import telemetry

    tags = {"node": telemetry.node_tag()}
    telemetry.set_gauge("ray_tpu_object_store_used_bytes", used_bytes,
                        tags)
    telemetry.set_gauge("ray_tpu_object_store_objects", num_objects,
                        tags)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def segment_name(object_id: ObjectID) -> str:
    # /dev/shm names are limited to NAME_MAX; 20-byte hex = 40 chars is fine.
    return f"rtpu_{object_id.hex()}"


class MemoryStore:
    """In-process object store with waiter support."""

    def __init__(self):
        self._objects: Dict[ObjectID, SerializedObject] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # Callbacks fired once when an object arrives (used by the async
        # runtime to resolve futures without polling).
        self._waiter_callbacks: Dict[ObjectID, List[Callable]] = {}

    def put(self, object_id: ObjectID, obj: SerializedObject):
        with self._cv:
            self._objects[object_id] = obj
            callbacks = self._waiter_callbacks.pop(object_id, [])
            self._cv.notify_all()
        for cb in callbacks:
            cb(obj)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_if_exists(self, object_id: ObjectID) -> Optional[SerializedObject]:
        with self._lock:
            return self._objects.get(object_id)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None
            ) -> Optional[SerializedObject]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while object_id not in self._objects:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cv.wait(remaining)
            return self._objects[object_id]

    def add_waiter(self, object_id: ObjectID, callback: Callable) -> bool:
        """Register callback(obj); fires immediately if present.

        Returns True if the object was already present (callback fired).
        """
        with self._lock:
            obj = self._objects.get(object_id)
            if obj is None:
                self._waiter_callbacks.setdefault(object_id, []).append(callback)
                return False
        callback(obj)
        return True

    def delete(self, object_id: ObjectID):
        with self._lock:
            self._objects.pop(object_id, None)
            self._waiter_callbacks.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)


class ShmStore:
    """Node-wide shared-memory store (plasma equivalent).

    One instance runs authoritative bookkeeping (in the node daemon /
    head process): capacity accounting, LRU eviction, pinning. Worker
    processes use `open_object` directly (zero-copy map by name) after the
    control plane confirms the object is sealed.
    """

    HEADER_MAGIC = b"RTPU"

    def __init__(self, capacity_bytes: int, spill_threshold: float = 0.8):
        self.capacity = capacity_bytes
        # Spill files land in the module-level spill_dir() (overridable
        # via RAY_TPU_OBJECT_SPILLING_DIR, exported by the head node).
        self.spill_threshold = spill_threshold
        self._used = 0
        from ray_tpu.util.locks import make_lock

        self._lock = make_lock("object_store.ShmStore._lock")
        # object hex -> (size, sealed, pinned_count); LRU order = insertion /
        # last-touch order.
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._spilled: Dict[str, str] = {}  # object hex -> file path

    # ---- creation path (writer side) ----

    @staticmethod
    def pack(obj: SerializedObject) -> bytes:
        """Serialize an object into the flat segment layout.

        Layout: magic | u32 header_len | msgpack header | inband | buffers
        (each aligned to 64 bytes).
        """
        header = {
            "metadata": obj.metadata,
            "inband_len": len(obj.inband),
            "buffer_lens": [memoryview(b).nbytes for b in obj.buffers],
        }
        hbytes = msgpack.packb(header)
        parts = [ShmStore.HEADER_MAGIC, len(hbytes).to_bytes(4, "little"), hbytes]
        offset = sum(len(p) for p in parts)
        pad = _aligned(offset) - offset
        parts.append(b"\x00" * pad)
        parts.append(obj.inband)
        offset = _aligned(offset) + len(obj.inband)
        for buf in obj.buffers:
            pad = _aligned(offset) - offset
            parts.append(b"\x00" * pad)
            mv = memoryview(buf).cast("B")
            parts.append(mv)
            offset = _aligned(offset) + mv.nbytes
        return b"".join(parts)

    @staticmethod
    def pack_into(obj: SerializedObject, out) -> int:
        """Write the flat layout of ``pack`` directly into a writable
        buffer of at least ``packed_size`` bytes (an arena slot or shm
        segment) — one memcpy per payload buffer instead of join-then-
        copy. Returns the packed length."""
        header = {
            "metadata": obj.metadata,
            "inband_len": len(obj.inband),
            "buffer_lens": [memoryview(b).nbytes for b in obj.buffers],
        }
        hbytes = msgpack.packb(header)
        out = memoryview(out).cast("B")
        out[0:4] = ShmStore.HEADER_MAGIC
        out[4:8] = len(hbytes).to_bytes(4, "little")
        out[8:8 + len(hbytes)] = hbytes
        offset = _aligned(8 + len(hbytes))
        out[offset:offset + len(obj.inband)] = obj.inband
        offset += len(obj.inband)
        for buf in obj.buffers:
            start = _aligned(offset)
            mv = memoryview(buf).cast("B")
            out[start:start + mv.nbytes] = mv
            offset = start + mv.nbytes
        return offset

    @staticmethod
    def packed_size(obj: SerializedObject) -> int:
        header = {
            "metadata": obj.metadata,
            "inband_len": len(obj.inband),
            "buffer_lens": [memoryview(b).nbytes for b in obj.buffers],
        }
        hbytes = msgpack.packb(header)
        offset = len(ShmStore.HEADER_MAGIC) + 4 + len(hbytes)
        offset = _aligned(offset) + len(obj.inband)
        for b in obj.buffers:
            offset = _aligned(offset) + memoryview(b).nbytes
        return offset

    def create_and_seal(self, object_id: ObjectID, obj: SerializedObject) -> int:
        """Write an object into a new shm segment. Returns its size."""
        size = self.packed_size(obj)
        self._reserve(object_id.hex(), size)
        try:
            seg = shared_memory.SharedMemory(
                name=segment_name(object_id), create=True, size=max(size, 1)
            )
        except FileExistsError:
            # Idempotent create (e.g. task retry re-produced the object).
            self._release(object_id.hex())
            return size
        try:
            self.pack_into(obj, seg.buf)
        finally:
            seg.close()
        with self._lock:
            if object_id.hex() in self._entries:
                self._entries[object_id.hex()]["sealed"] = True
        self._report_usage()
        return size

    def _report_usage(self):
        _report_store_usage(self.used_bytes(), self.num_objects())

    def _reserve(self, hex_id: str, size: int):
        with self._lock:
            if hex_id in self._entries:
                raise FileExistsError(hex_id)
            self._evict_for(size)
            if self._used + size > self.capacity:
                raise ObjectStoreFullError(
                    f"object of {size} bytes does not fit: "
                    f"{self._used}/{self.capacity} used"
                )
            self._used += size
            # Primary-copy pin (matches the native arena): eviction must
            # never drop an object its owner still references; overflow
            # surfaces as ObjectStoreFullError and spills to disk.
            self._entries[hex_id] = {"size": size, "sealed": False,
                                     "pins": 1}

    def _release(self, hex_id: str):
        with self._lock:
            entry = self._entries.pop(hex_id, None)
            if entry:
                self._used -= entry["size"]

    def _evict_for(self, size: int):
        """LRU-evict unpinned sealed objects until `size` fits under the
        soft limit (``spill_threshold`` × capacity — headroom so writers
        rarely hit the hard cap; reference: local_object_manager spilling
        at the high-water mark). Lock held."""
        soft = int(self.capacity * self.spill_threshold)
        if self._used + size <= soft:
            return
        victims = []
        for hex_id, entry in self._entries.items():
            if self._used + size <= soft:
                break
            if entry["sealed"] and entry["pins"] == 0:
                victims.append(hex_id)
                self._used -= entry["size"]
        for hex_id in victims:
            del self._entries[hex_id]
            _unlink_segment(hex_id)

    # ---- read path (any process) ----

    # Process-wide cache of mapped segments. Mappings are kept until the
    # process exits or the object is freed — zero-copy views handed to user
    # code (numpy arrays aliasing the segment) must outlive any one
    # SerializedObject, so segments are never closed implicitly.
    _open_segments: Dict[str, shared_memory.SharedMemory] = {}
    _open_lock = threading.Lock()

    @staticmethod
    def open_object(object_id: ObjectID) -> Optional[SerializedObject]:
        """Zero-copy map of a sealed object. Returns None if absent."""
        name = segment_name(object_id)
        with ShmStore._open_lock:
            seg = ShmStore._open_segments.get(name)
            if seg is None:
                try:
                    seg = shared_memory.SharedMemory(name=name)
                except FileNotFoundError:
                    return None
                ShmStore._open_segments[name] = seg
        buf = seg.buf
        if bytes(buf[:4]) != ShmStore.HEADER_MAGIC:
            # Segment exists but is not (fully) written yet — drop it from
            # the cache so a later retry re-maps instead of seeing a
            # poisoned closed segment.
            with ShmStore._open_lock:
                ShmStore._open_segments.pop(name, None)
            _close_or_neuter(seg)
            return None
        return parse_packed(buf)

    # ---- lifetime management (authoritative instance) ----

    def mark_sealed(self, object_id: ObjectID, size: int):
        """Record an object sealed by another process on this node."""
        hex_id = object_id.hex()
        with self._lock:
            if hex_id not in self._entries:
                self._evict_for(size)
                self._used += size
                self._entries[hex_id] = {"size": size, "sealed": True,
                                         "pins": 1}  # primary-copy pin
            else:
                self._entries[hex_id]["sealed"] = True
            self._entries.move_to_end(hex_id)
        self._report_usage()

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id.hex())
            return bool(entry and entry["sealed"])

    def pin(self, object_id: ObjectID):
        with self._lock:
            entry = self._entries.get(object_id.hex())
            if entry:
                entry["pins"] += 1

    def unpin(self, object_id: ObjectID):
        with self._lock:
            entry = self._entries.get(object_id.hex())
            if entry and entry["pins"] > 0:
                entry["pins"] -= 1

    def delete(self, object_id: ObjectID):
        hex_id = object_id.hex()
        self._release(hex_id)
        _unlink_segment(hex_id)
        spill_delete(object_id)
        self._report_usage()

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def num_objects(self) -> int:
        with self._lock:
            return len(self._entries)

    def cleanup(self):
        with self._lock:
            hex_ids = list(self._entries)
            self._entries.clear()
            self._used = 0
        for hex_id in hex_ids:
            _unlink_segment(hex_id)


def packed_length(buf) -> Optional[int]:
    """Exact byte length of a packed payload, from its header. Segment /
    arena slots are page- or alignment-rounded above the payload; serving
    the rounded view would transfer trailing garbage and make the pulled
    copy's size disagree with the directory's sealed size."""
    if bytes(buf[:4]) != ShmStore.HEADER_MAGIC:
        return None
    hlen = int.from_bytes(buf[4:8], "little")
    header = msgpack.unpackb(bytes(buf[8:8 + hlen]))
    offset = _aligned(8 + hlen) + header["inband_len"]
    for blen in header["buffer_lens"]:
        offset = _aligned(offset) + blen
    return offset


def parse_packed(buf) -> Optional[SerializedObject]:
    """Parse the flat packed layout (ShmStore.pack) from any buffer —
    an shm segment or a native-arena view — keeping payload buffers
    zero-copy."""
    if bytes(buf[:4]) != ShmStore.HEADER_MAGIC:
        return None
    hlen = int.from_bytes(buf[4:8], "little")
    header = msgpack.unpackb(bytes(buf[8:8 + hlen]))
    offset = _aligned(8 + hlen)
    inband = bytes(buf[offset:offset + header["inband_len"]])
    offset = _aligned(offset) + header["inband_len"]
    buffers = []
    for blen in header["buffer_lens"]:
        start = _aligned(offset)
        buffers.append(buf[start:start + blen])
        offset = start + blen
    return SerializedObject(
        metadata=header["metadata"], inband=inband, buffers=buffers
    )


class NativeShmStore:
    """Head-side bookkeeping over the native C++ arena — the same
    authoritative interface as ShmStore, with allocation/LRU/eviction
    delegated to cpp/tpustore (which is shared by every process on the
    node, so worker writes hit the same accounting)."""

    def __init__(self, arena):
        self.arena = arena
        self.capacity = arena.capacity()

    def create_and_seal(self, object_id: ObjectID,
                        obj: SerializedObject) -> int:
        size = ShmStore.packed_size(obj)
        reserved = self.arena.create_reserve(object_id.binary(), size)
        if reserved is None:
            return size  # idempotent re-produce
        idx, view = reserved
        try:
            ShmStore.pack_into(obj, view)
        finally:
            del view
        self.arena.seal_reserved(idx, object_id.binary())
        self._report_usage()
        return size

    def _report_usage(self):
        _report_store_usage(self.used_bytes(), self.num_objects())

    def mark_sealed(self, object_id: ObjectID, size: int):
        # The arena is authoritative; the seal already happened in the
        # producing process.
        pass

    def open_object(self, object_id: ObjectID) -> Optional[SerializedObject]:
        view = self.arena.lookup(object_id.binary())
        if view is None:
            return None
        return parse_packed(view)

    def contains(self, object_id: ObjectID) -> bool:
        return self.arena.contains(object_id.binary())

    def pin(self, object_id: ObjectID):
        self.arena.pin(object_id.binary())

    def unpin(self, object_id: ObjectID):
        self.arena.unpin(object_id.binary())

    def delete(self, object_id: ObjectID):
        self.arena.delete(object_id.binary())
        spill_delete(object_id)
        self._report_usage()

    def used_bytes(self) -> int:
        return self.arena.used_bytes()

    def num_objects(self) -> int:
        return self.arena.num_objects()

    def cleanup(self):
        self.arena.destroy()


def spill_dir() -> str:
    """Directory for objects that overflow shared memory (reference:
    fallback allocation + object spilling, local_object_manager.h:41 /
    external_storage.py)."""
    override = os.environ.get("RAY_TPU_OBJECT_SPILLING_DIR")
    if override:
        return override
    base = os.environ.get("RAY_TPU_SESSION_DIR")
    if base:
        return os.path.join(base, "spill")
    import tempfile

    return os.path.join(tempfile.gettempdir(), "ray_tpu", "spill")


def _spill_path(object_id: ObjectID) -> str:
    return os.path.join(spill_dir(), object_id.hex())


def _spill_write(object_id: ObjectID, data: bytes) -> int:
    from ray_tpu.util import telemetry

    t0 = time.time()
    path = _spill_path(object_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    telemetry.inc("ray_tpu_object_spilled_total")
    telemetry.inc("ray_tpu_object_spilled_bytes_total", len(data))
    telemetry.event("objects", f"spill {object_id.hex()[:8]}", ts=t0,
                    dur=time.time() - t0, args={"bytes": len(data)})
    from ray_tpu.util import flight_recorder

    flight_recorder.record("object", "spilled",
                           object=object_id.hex()[:16],
                           bytes=len(data))
    return len(data)


def _spill_open(object_id: ObjectID) -> Optional[SerializedObject]:
    """mmap a spilled object — page-cache-backed zero-copy buffers."""
    import mmap

    path = _spill_path(object_id)
    try:
        f = open(path, "rb")
    except FileNotFoundError:
        return None
    try:
        mapped = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    finally:
        f.close()
    obj = parse_packed(memoryview(mapped))
    if obj is not None:
        from ray_tpu.util import flight_recorder, telemetry

        telemetry.inc("ray_tpu_object_restored_total")
        telemetry.event("objects", f"restore {object_id.hex()[:8]}")
        flight_recorder.record("object", "restored",
                               object=object_id.hex()[:16])
    return obj


# Serve-side cache of spill mmaps (object hex -> memoryview); dropped on
# spill_delete. The mapping keeps the file's pages reachable even after
# unlink, which is exactly the hand-a-view-out semantics readers need.
_spill_mmaps: Dict[str, memoryview] = {}
_spill_mmap_lock = threading.Lock()


def spill_delete(object_id: ObjectID) -> None:
    with _spill_mmap_lock:
        _spill_mmaps.pop(object_id.hex(), None)
    try:
        os.remove(_spill_path(object_id))
    except OSError:
        pass


def node_store_write(object_id: ObjectID, obj: SerializedObject) -> int:
    """Worker-side write of a large object to the node store. Packs IN
    PLACE into the destination slot (pack_into) — the single memcpy per
    payload buffer is the whole write cost, which is what put bandwidth
    is made of."""
    return _node_store_put(
        object_id, ShmStore.packed_size(obj),
        fill=lambda view: ShmStore.pack_into(obj, view),
        pack_bytes=lambda: ShmStore.pack(obj),
        primary=True)


def node_store_write_packed(object_id: ObjectID, data,
                            primary: bool = True) -> int:
    """Write an already-packed payload to the node store (the local write
    path and the cross-node pull ingest both land here).

    ``primary=False`` marks a borrowed copy pulled from another node: it
    carries no eviction guard, so local memory pressure can drop it and a
    consumer re-pulls (the authoritative copy lives with the owner)."""
    mv = memoryview(data).cast("B")

    def fill(view):
        view = memoryview(view).cast("B")
        view[:mv.nbytes] = mv

    return _node_store_put(object_id, mv.nbytes, fill=fill,
                           pack_bytes=lambda: data, primary=primary)


def _report_arena_usage(arena) -> None:
    """Node-store gauges from the shared arena's accounting — the
    arena is the authority every process on the node writes through."""
    try:
        _report_store_usage(arena.used_bytes(), arena.num_objects())
    except Exception:
        pass


def _node_store_put(object_id: ObjectID, size: int, fill, pack_bytes,
                    primary: bool) -> int:
    """One store-selection policy for both the local write path
    (pack-into-slot) and the pull-ingest path (copy packed bytes):
    native arena when attached, else a per-object shm segment, spilling
    to disk when neither fits. ``fill(view)`` writes the payload in
    place; ``pack_bytes()`` materializes it only if the spill path
    needs a bytes object."""
    from ray_tpu.core import native_store

    arena = native_store.get_attached_arena()
    if arena is not None:
        try:
            reserved = arena.create_reserve(object_id.binary(), size)
        except ObjectStoreFullError:
            return _spill_write(object_id, pack_bytes())
        if reserved is None:
            return size  # idempotent re-produce
        idx, view = reserved
        try:
            fill(view)
        finally:
            del view  # release the slot view before sealing
        arena.seal_reserved(idx, object_id.binary(),
                            pin_primary=primary)
        _report_arena_usage(arena)
        return size
    try:
        seg = shared_memory.SharedMemory(
            name=segment_name(object_id), create=True, size=max(size, 1))
    except FileExistsError:
        return size
    except OSError:
        return _spill_write(object_id, pack_bytes())
    try:
        fill(seg.buf)
    finally:
        seg.close()
    return size


MADV_POPULATE_READ = 22
MADV_POPULATE_WRITE = 23


def populate_range(view: memoryview,
                   advice: int = MADV_POPULATE_READ) -> None:
    """Batch-fault a mapped range into this process's page table.
    Measured on this infrastructure: POPULATE_READ of an existing
    range is ~30ms/GiB (worth it before a bulk copy from a
    freshly-attached mapping); POPULATE_WRITE is pathologically SLOW
    (~60µs/page ≈ 16s/GiB, far worse than lazy write faults at
    ~2µs/page) — do NOT use it on ingest destinations. Arena views are
    writable, so from_buffer always yields the address. Best-effort:
    kernels without the flag keep lazy faulting."""
    try:
        import ctypes

        addr = ctypes.addressof(ctypes.c_char.from_buffer(view))
        page = 4096
        base = addr & ~(page - 1)
        length = (addr - base) + view.nbytes
        libc = ctypes.CDLL(None, use_errno=True)
        libc.madvise.restype = ctypes.c_int
        libc.madvise.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                 ctypes.c_int]
        libc.madvise(ctypes.c_void_p(base), ctypes.c_size_t(length),
                     advice)
    except Exception:
        pass



#: node_store_reserve sentinel: the object is already present locally.
ALREADY_PRESENT = object()


class NodeStoreWriter:
    """Pre-allocated destination for a streaming ingest (cross-node
    pull): chunks land in place at their offsets, so a multi-GiB pull
    holds one chunk of Python memory, not the whole object (reference:
    object_manager.cc writes chunks straight into the plasma create
    buffer)."""

    def __init__(self, kind: str, object_id: ObjectID, size: int,
                 arena=None, idx=None, view=None, seg=None, path=None,
                 final_path=None):
        self._kind = kind  # "arena" | "shm" | "spill"
        self._object_id = object_id
        self._size = size
        self._arena = arena
        self._idx = idx
        self._view = view
        self._seg = seg
        self._path = path          # spill: TEMP path during ingest
        self._final_path = final_path
        self._file = open(path, "r+b") if kind == "spill" else None
        # shm segments have no create/seal state machine: readers gate
        # on HEADER_MAGIC, so the magic bytes are withheld until seal().
        self._magic: Optional[bytes] = None

    def direct_view(self) -> Optional[memoryview]:
        """Writable full-size view for zero-copy ingest (the data-plane
        puller recv_into()s socket bytes straight into the slot). Arena
        slots only: the shm-segment kind gates readers on a magic
        prefix that a direct write would publish too early, and spill
        has no memory view."""
        if self._kind == "arena":
            return self._view
        return None

    def write_at(self, offset: int, data) -> None:
        if self._kind == "spill":
            os.pwrite(self._file.fileno(), bytes(data), offset)
            return
        mv = memoryview(data).cast("B")
        if self._kind == "shm" and offset == 0 and mv.nbytes >= 4:
            self._magic = bytes(mv[:4])
            mv = mv[4:]
            offset = 4
            if not mv.nbytes:
                return
        buf = self._view if self._kind == "arena" else self._seg.buf
        buf[offset:offset + mv.nbytes] = mv

    def seal(self) -> None:
        if self._kind == "arena":
            del self._view
            self._arena.seal_reserved(self._idx,
                                      self._object_id.binary(),
                                      pin_primary=False)
            _report_arena_usage(self._arena)
        elif self._kind == "shm":
            if self._magic is not None:
                self._seg.buf[0:4] = self._magic  # publish LAST
            self._seg.close()
        else:
            self._file.close()
            os.replace(self._path, self._final_path)

    def abort(self) -> None:
        """Discard a partial ingest (holder died / chunk missing)."""
        try:
            if self._kind == "arena":
                del self._view
                # Delete FIRST (store.cc handles kCreated: marks the
                # entry zombie), THEN seal — which returns TS_ESTATE and
                # frees. Seal-then-delete would expose the garbage as a
                # briefly-readable sealed object.
                self._arena.delete(self._object_id.binary())
                self._arena.seal_reserved(self._idx,
                                          self._object_id.binary(),
                                          pin_primary=False)
            elif self._kind == "shm":
                # Magic never published: readers always saw not-ready.
                self._seg.close()
                _unlink_segment(self._object_id.hex())
            else:
                self._file.close()
                os.remove(self._path)
        except Exception:
            pass


def node_store_reserve(object_id: ObjectID, size: int):
    """Allocate a local destination of ``size`` bytes for a streaming
    ingest. Returns a NodeStoreWriter, or ALREADY_PRESENT when a local
    copy exists (concurrent pull landed first)."""
    from ray_tpu.core import native_store

    arena = native_store.get_attached_arena()
    if arena is not None:
        try:
            reserved = arena.create_reserve(object_id.binary(), size)
        except ObjectStoreFullError:
            reserved = None  # overflow: spill destination below
        if reserved is not None:
            idx, view = reserved
            return NodeStoreWriter("arena", object_id, size,
                                   arena=arena, idx=idx, view=view)
        if arena.contains(object_id.binary()):
            return ALREADY_PRESENT
    else:
        try:
            seg = shared_memory.SharedMemory(
                name=segment_name(object_id), create=True,
                size=max(size, 1))
            return NodeStoreWriter("shm", object_id, size, seg=seg)
        except FileExistsError:
            # The segment may belong to a STILL-RUNNING concurrent
            # ingest in another process (puller dedup is per-process).
            # Only a published magic marks it complete; otherwise join
            # the ingest — both writers write identical bytes of the
            # same sealed object, and whichever seal()s first publishes.
            try:
                seg = shared_memory.SharedMemory(
                    name=segment_name(object_id))
            except OSError:
                return ALREADY_PRESENT  # vanished: freed after seal
            if bytes(seg.buf[:4]) == ShmStore.HEADER_MAGIC:
                seg.close()
                return ALREADY_PRESENT
            return NodeStoreWriter("shm", object_id, size, seg=seg)
        except OSError:
            pass  # /dev/shm full: spill destination
    final_path = _spill_path(object_id)
    os.makedirs(os.path.dirname(final_path), exist_ok=True)
    tmp = final_path + f".ingest{os.getpid()}"
    with open(tmp, "wb") as f:
        f.truncate(size)
    return NodeStoreWriter("spill", object_id, size, path=tmp,
                           final_path=final_path)


def node_store_open(object_id: ObjectID) -> Optional[SerializedObject]:
    """Worker-side zero-copy read from the node store (arena or
    per-segment shm, falling back to the disk spill area)."""
    from ray_tpu.core import native_store

    arena = native_store.get_attached_arena()
    if arena is not None:
        view = arena.lookup(object_id.binary())
        if view is not None:
            return parse_packed(view)
        return _spill_open(object_id)
    obj = ShmStore.open_object(object_id)
    if obj is not None:
        return obj
    return _spill_open(object_id)


def node_store_arena_name(object_id: ObjectID) -> Optional[str]:
    """Name of this process's attached arena IF it holds the object —
    advertised in fetch_object_meta so a same-host puller can attach
    the arena and memcpy instead of round-tripping through loopback
    TCP (reference: plasma's same-node objects are shared, never
    socket-copied)."""
    from ray_tpu.core import native_store

    arena = native_store.get_attached_arena()
    if arena is not None and arena.contains(object_id.binary()):
        return arena.name
    return None


def node_store_read_packed(object_id: ObjectID):
    """Raw packed payload of a sealed object in this node's store, as a
    zero-copy buffer when possible (serve side of cross-node transfer).
    Returns None if the object is not on this node."""
    from ray_tpu.core import native_store

    arena = native_store.get_attached_arena()
    if arena is not None:
        view = arena.lookup(object_id.binary())
        if view is not None:
            exact = packed_length(view)
            return view if exact is None else view[:exact]
    else:
        name = segment_name(object_id)
        with ShmStore._open_lock:
            seg = ShmStore._open_segments.get(name)
        if seg is None:
            try:
                seg = shared_memory.SharedMemory(name=name)
            except (FileNotFoundError, OSError):
                seg = None
            else:
                with ShmStore._open_lock:
                    ShmStore._open_segments.setdefault(name, seg)
        if seg is not None and bytes(seg.buf[:4]) == ShmStore.HEADER_MAGIC:
            exact = packed_length(seg.buf)
            return seg.buf if exact is None else seg.buf[:exact]
    # Spilled: mmap once per object and serve every chunk request from
    # the cached mapping (mirrors ShmStore._open_segments for shm).
    hex_id = object_id.hex()
    with _spill_mmap_lock:
        cached = _spill_mmaps.get(hex_id)
    if cached is not None:
        return cached
    import mmap

    path = _spill_path(object_id)
    try:
        f = open(path, "rb")
    except OSError:
        return None
    try:
        mapped = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except ValueError:  # empty file
        return b""
    finally:
        f.close()
    view = memoryview(mapped)
    with _spill_mmap_lock:
        _spill_mmaps[hex_id] = view
    return view


def _unlink_segment(hex_id: str):
    name = f"rtpu_{hex_id}"
    with ShmStore._open_lock:
        seg = ShmStore._open_segments.pop(name, None)
    try:
        if seg is None:
            seg = shared_memory.SharedMemory(name=name)
        seg.unlink()
        _close_or_neuter(seg)
    except FileNotFoundError:
        pass


def _close_or_neuter(seg: shared_memory.SharedMemory):
    """Close a segment; if user views still alias it, intentionally leak the
    mapping (zero-copy safety) and disarm __del__ so it doesn't retry."""
    try:
        seg.close()
    except BufferError:
        seg._buf = None
        seg._mmap = None


def default_capacity(proportion: float = 0.3) -> int:
    try:
        import psutil

        return int(psutil.virtual_memory().total * proportion)
    except Exception:
        return 2 * 1024**3
