"""TPU detection and node resource shaping.

Behavioral equivalent of the reference's TPUAcceleratorManager
(reference: python/ray/_private/accelerators/tpu.py:75): detect chips on the
host, expose the ``TPU`` resource, and — when the host is part of a pod
slice — add the synthetic gang resource ``TPU-<topology>-head`` on worker 0
of the slice so slice-wide workloads can anchor one gang per slice
(reference: tpu.py:335,382).

Detection order: JAX runtime (authoritative when importable), then GCE/GKE
environment variables (reference: tpu.py:52,101), then nothing.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional

logger = logging.getLogger(__name__)

# Chip counts that can be claimed by a single task (reference: tpu.py:13,144
# — single-host TPU VMs expose 1, 2, 4, or 8 chips).
VALID_CHIP_COUNTS = (1, 2, 4, 8)


class TPUAcceleratorManager:
    resource_name = "TPU"

    @staticmethod
    def detect_num_chips() -> int:
        # Explicit override — set by operators and propagated to child
        # processes so only one process ever probes the hardware.
        raw = os.environ.get("RAY_TPU_NUM_CHIPS")
        if raw:
            try:
                return int(raw)
            except ValueError:
                pass
        # Consult the JAX runtime only if THIS process already
        # initialized it. A cold jax backend init grabs the TPU runtime
        # (libtpu is single-client per chip); a control-plane process —
        # head, node agent — cold-probing here would block startup on a
        # chip another process holds. Compute processes that own the
        # chip have the backend live and get the authoritative count.
        try:
            import sys

            xb = sys.modules.get("jax._src.xla_bridge")
            if xb is not None and getattr(xb, "_backends", None):
                import jax

                n = sum(1 for d in jax.local_devices()
                        if d.platform != "cpu")
                if n > 0:
                    return n
        except Exception:
            pass
        # GCE metadata env (set on TPU VMs).
        chips = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
        if chips:
            try:
                dims = [int(x) for x in chips.split(",")]
                n = 1
                for d in dims:
                    n *= d
                return n
            except ValueError:
                pass
        visible = os.environ.get("TPU_VISIBLE_CHIPS")
        if visible:
            return len([c for c in visible.split(",") if c.strip()])
        return 0

    @staticmethod
    def detect_pod_type() -> Optional[str]:
        """E.g. 'v5litepod-64' when this host is part of a pod slice."""
        accel_type = os.environ.get("TPU_ACCELERATOR_TYPE")
        if accel_type:
            return accel_type
        return None

    @staticmethod
    def detect_worker_id() -> int:
        for var in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"):
            raw = os.environ.get(var)
            if raw is not None:
                try:
                    return int(raw)
                except ValueError:
                    pass
        return 0

    @classmethod
    def node_resources(cls) -> Dict[str, float]:
        """Resources this host contributes to the cluster."""
        out: Dict[str, float] = {}
        num_chips = cls.detect_num_chips()
        if num_chips <= 0:
            return out
        out[cls.resource_name] = float(num_chips)
        pod_type = cls.detect_pod_type()
        if pod_type and cls.detect_worker_id() == 0:
            # Gang anchor: exactly one per slice, on worker 0
            # (reference: tpu.py get_current_node_additional_resources :335).
            out[f"TPU-{pod_type}-head"] = 1.0
        return out

    @staticmethod
    def validate_chip_request(num_chips: float) -> None:
        if num_chips != int(num_chips) or int(num_chips) not in VALID_CHIP_COUNTS:
            raise ValueError(
                f"TPU requests must be one of {VALID_CHIP_COUNTS} chips, "
                f"got {num_chips} (use a placement group for multi-host "
                "slices)"
            )

    @staticmethod
    def set_visible_chips_env(chip_ids) -> None:
        """Per-worker chip isolation (reference: tpu.py:158-192
        TPU_VISIBLE_CHIPS)."""
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chip_ids)
