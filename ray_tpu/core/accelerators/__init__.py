from ray_tpu.core.accelerators.tpu import TPUAcceleratorManager

__all__ = ["TPUAcceleratorManager"]
