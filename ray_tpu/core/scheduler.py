"""Cluster scheduler, scheduling policies, and the worker pool.

Reference mapping:
- ``ClusterScheduler`` ≈ ClusterTaskManager + ClusterResourceScheduler
  (reference: src/ray/raylet/scheduling/cluster_task_manager.h:42): queue a
  lease request → pick a node by policy → dispatch to that node's worker
  pool → grant the lease; infeasible requests park until resources appear.
- Policies ≈ src/ray/raylet/scheduling/policy/ — hybrid (default), spread,
  node-affinity, placement-group bundle packing.
- ``WorkerPool`` ≈ src/ray/raylet/worker_pool.h:156 — spawns/pools worker
  processes, prestarts idle workers, hands leased workers out.

In this single-host runtime the head process owns every virtual node's pool;
the node abstraction (NodeID + ResourceSet + pool) is what multi-host
deployment shards across machines.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ray_tpu.core.ids import NodeID, PlacementGroupID, WorkerID
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.task_spec import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
    TaskSpec,
)

logger = logging.getLogger(__name__)


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    node_id: NodeID
    pid: int
    address: Optional[tuple] = None  # (host, port) once registered
    connection: object = None  # head<->worker Connection once registered
    state: str = "STARTING"  # STARTING | IDLE | LEASED | ACTOR | DEAD
    lease_id: Optional[str] = None
    started_at: float = field(default_factory=time.monotonic)
    # Memory-monitor victim ranking: does the current work survive a
    # kill for free (task with retries left / restartable actor)?
    task_retriable: bool = True
    task_started_at: float = 0.0


@dataclass
class PendingLease:
    spec: TaskSpec
    resources: ResourceSet
    future: asyncio.Future  # resolves to WorkerHandle
    is_actor_creation: bool = False
    queued_at: float = field(default_factory=time.monotonic)
    # Why this lease is still pending, refreshed by every _pick_node
    # attempt — the `ray_tpu debug why` explainer reads it live, and the
    # flight recorder logs it whenever it CHANGES (not per pump tick).
    wait_reason: str = ""
    _reason_recorded: str = field(default="", repr=False)


@dataclass
class BundleState:
    resources: ResourceSet
    node_id: NodeID
    # Available portion of the reservation (tasks in the PG consume this).
    available: ResourceSet = None

    def __post_init__(self):
        if self.available is None:
            self.available = self.resources


class Node:
    def __init__(self, node_id: NodeID, resources: ResourceSet,
                 labels: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.resources = NodeResources(resources)
        self.labels = labels or {}
        self.state = "ALIVE"


def apply_worker_bytecode_cache(env: dict) -> None:
    """Give spawned workers a writable bytecode cache. Spawn cost is
    dominated by module compilation when the environment disables
    bytecode caching (PYTHONDONTWRITEBYTECODE is common in containers):
    ~10s of compile() per worker for the jax import chain. The cache is
    keyed by uid and created 0700 — a world-shared /tmp path would let
    one user plant .pyc files that another user's workers execute."""
    env.pop("PYTHONDONTWRITEBYTECODE", None)
    cache = env.get("PYTHONPYCACHEPREFIX")
    if not cache:
        cache = os.path.join(tempfile.gettempdir(),
                             f"ray_tpu_pycache-{os.getuid()}")
        env["PYTHONPYCACHEPREFIX"] = cache
    try:
        os.makedirs(cache, mode=0o700, exist_ok=True)
    except OSError:
        env.pop("PYTHONPYCACHEPREFIX", None)


def filter_worker_pythonpath(parts: List[str]) -> List[str]:
    """Drop PYTHONPATH entries matched by RAY_TPU_WORKER_PYTHONPATH_
    EXCLUDE (comma-separated substrings) from worker environments.

    Chip-less workers must not load accelerator site hooks (PJRT plugin
    registration via sitecustomize): a tunneled-TPU hook in a pure
    control-plane process adds ~4ms to every cross-process wakeup. The
    head (and node agents) set the exclusion when the node contributes
    no TPU resource — one process per chip owns the accelerator
    runtime; everyone else stays lean."""
    exclude = os.environ.get("RAY_TPU_WORKER_PYTHONPATH_EXCLUDE")
    if not exclude:
        return parts
    subs = [s for s in exclude.split(",") if s]
    return [p for p in parts if not any(s in p for s in subs)]


class WorkerPool:
    """Spawns and pools worker processes for the cluster's nodes."""

    def __init__(self, head_host: str, head_port: int, session_dir: str,
                 on_worker_exit: Optional[Callable] = None):
        self.head_host = head_host
        self.head_port = head_port
        self.session_dir = session_dir
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        # node_id -> list of idle registered workers
        self.idle: Dict[NodeID, List[WorkerHandle]] = {}
        # Workers spawned but not yet registered.
        self.starting: Dict[WorkerID, WorkerHandle] = {}
        self._procs: Dict[WorkerID, subprocess.Popen] = {}
        self._forkserver = None  # lazily started ForkserverClient
        self.on_worker_exit = on_worker_exit
        # Remote-node hooks (set by the head): spawn_remote(node_id,
        # worker_id) -> bool returns True when the node's agent handles
        # the fork; kill_remote(node_id, worker_id) forwards a kill.
        self.spawn_remote: Optional[Callable] = None
        self.kill_remote: Optional[Callable] = None

    def spawn(self, node_id: NodeID, env_overrides: Optional[dict] = None
              ) -> Optional[WorkerHandle]:
        """Start a worker for node_id. Returns None when the spawn is
        DEFERRED — the forkserver is still preimporting (~2.5 s) and a
        cold Popen herd would be slower than waiting for it; the
        scheduling pump recomputes the deficit and retries next tick."""
        worker_id = WorkerID.from_random()
        if self.spawn_remote is not None and self.spawn_remote(node_id,
                                                               worker_id):
            # pid -1 marks an agent-managed process: no local Popen to
            # poll; early deaths arrive as worker_exited_early reports.
            handle = WorkerHandle(worker_id=worker_id, node_id=node_id,
                                  pid=-1)
            self.workers[worker_id] = handle
            self.starting[worker_id] = handle
            return handle
        env = dict(os.environ)
        env.update(env_overrides or {})
        env["RAY_TPU_HEAD_HOST"] = self.head_host
        env["RAY_TPU_HEAD_PORT"] = str(self.head_port)
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_ID"] = node_id.hex()
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        # Ensure the worker can import ray_tpu regardless of its cwd.
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(ray_tpu.__file__))
        existing = env.get("PYTHONPATH", "")
        # Workers inherit the driver's import environment (the reference
        # ships the job's working_dir / py_modules through runtime envs;
        # in-process clusters just share sys.path) so by-reference pickles
        # of driver-module functions resolve.
        driver_paths = [p for p in sys.path if p and os.path.isdir(p)]
        parts = [pkg_root] + driver_paths + (
            existing.split(os.pathsep) if existing else [])
        seen, ordered = set(), []
        for p in parts:
            if p not in seen:
                seen.add(p)
                ordered.append(p)
        env["PYTHONPATH"] = os.pathsep.join(
            filter_worker_pythonpath(ordered))
        apply_worker_bytecode_cache(env)
        log_path = os.path.join(self.session_dir, "logs",
                                f"worker-{worker_id.hex()[:12]}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        proc = self._spawn_proc(env, log_path)
        if proc is None:
            return None  # deferred until the forkserver is ready
        handle = WorkerHandle(worker_id=worker_id, node_id=node_id, pid=proc.pid)
        self.workers[worker_id] = handle
        self.starting[worker_id] = handle
        self._procs[worker_id] = proc
        return handle

    def _spawn_proc(self, env: dict, log_path: str):
        """Fork from the preimported forkserver when it's ready
        (ms-scale spawn); cold Popen otherwise. The forkserver starts in
        the background on first use — this method is called from the
        head's async pump, which must never block on the forkserver's
        ~2.5 s preimport, so early spawns pay the cold path instead."""
        from ray_tpu.core.config import get_config

        if os.name == "posix" and get_config().worker_forkserver:
            try:
                if self._forkserver is None:
                    from ray_tpu.core.forkserver import ForkserverClient

                    self._forkserver = ForkserverClient(
                        self.session_dir, env)
                    self._forkserver.start_async()
                if self._forkserver.ready():
                    return self._forkserver.spawn(env, log_path)
                if not self._forkserver.failed():
                    # Still preimporting: DEFER rather than cold-start a
                    # herd — a cold worker takes as long as the
                    # forkserver itself, and N of them serialize on one
                    # core while one preimport serves all N forks.
                    return None
            except Exception:
                logger.warning("forkserver spawn failed; falling back "
                               "to cold worker start", exc_info=True)
        with open(log_path, "ab") as log_file:
            return subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.worker_main"],
                env=env,
                stdout=log_file,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )

    def on_registered(self, worker_id: WorkerID, address: tuple, connection
                      ) -> Optional[WorkerHandle]:
        handle = self.starting.pop(worker_id, None)
        if handle is None:
            return None
        handle.address = address
        handle.connection = connection
        handle.state = "IDLE"
        self.idle.setdefault(handle.node_id, []).append(handle)
        return handle

    def starting_count(self, node_id: NodeID) -> int:
        return sum(1 for h in self.starting.values()
                   if h.node_id == node_id)

    # Remote workers whose agent never reports back (e.g. agent wedged)
    # are reaped on a generous registration deadline.
    REMOTE_REGISTER_TIMEOUT_S = 120.0

    def reap_exited_starting(self) -> List[WorkerHandle]:
        """Collect starting workers whose process died before registering."""
        dead = []
        now = time.monotonic()
        for wid, h in list(self.starting.items()):
            proc = self._procs.get(wid)
            if proc is not None and proc.poll() is not None:
                dead.append(self.mark_dead(wid))
            elif (proc is None and h.pid == -1 and
                  now - h.started_at > self.REMOTE_REGISTER_TIMEOUT_S):
                dead.append(self.mark_dead(wid))
        return [h for h in dead if h is not None]

    def pop_idle(self, node_id: NodeID) -> Optional[WorkerHandle]:
        idle = self.idle.get(node_id) or []
        while idle:
            handle = idle.pop()
            if handle.state == "IDLE":
                return handle
        return None

    def push_idle(self, handle: WorkerHandle):
        handle.state = "IDLE"
        handle.lease_id = None
        self.idle.setdefault(handle.node_id, []).append(handle)

    def mark_dead(self, worker_id: WorkerID) -> Optional[WorkerHandle]:
        handle = self.workers.pop(worker_id, None)
        self.starting.pop(worker_id, None)
        if handle:
            handle.state = "DEAD"
            idle = self.idle.get(handle.node_id)
            if idle and handle in idle:
                idle.remove(handle)
        proc = self._procs.pop(worker_id, None)
        if proc and proc.poll() is None:
            try:
                proc.terminate()
            except Exception:
                pass
        return handle

    def kill(self, worker_id: WorkerID):
        proc = self._procs.get(worker_id)
        if proc and proc.poll() is None:
            try:
                proc.kill()
            except Exception:
                pass
        handle = self.workers.get(worker_id)
        if (proc is None and handle is not None and handle.pid == -1
                and self.kill_remote is not None):
            self.kill_remote(handle.node_id, worker_id)
        self.mark_dead(worker_id)

    def shutdown(self):
        for worker_id in list(self._procs):
            self.kill(worker_id)
        if self._forkserver is not None:
            self._forkserver.stop()
            self._forkserver = None


class ClusterScheduler:
    """Queues lease requests and matches them to nodes/workers."""

    def __init__(self, pool: WorkerPool, spread_threshold: float = 0.5):
        self.pool = pool
        self.nodes: Dict[NodeID, Node] = {}
        self.pending: List[PendingLease] = []
        self.spread_threshold = spread_threshold
        # Placement groups: pg_id -> list[BundleState]
        self.pg_bundles: Dict[PlacementGroupID, List[BundleState]] = {}
        self._spread_rr = 0  # round-robin cursor for spread policy
        self._lease_counter = 0
        # lease_id -> (node_id, resources, pg, bundle_index) for release
        self.active_leases: Dict[str, tuple] = {}

    # ---- node management ----

    def add_node(self, node: Node):
        self.nodes[node.node_id] = node

    def remove_node(self, node_id: NodeID):
        node = self.nodes.pop(node_id, None)
        if node:
            node.state = "DEAD"

    # ---- placement groups ----

    def try_place_bundles(self, pg_id: PlacementGroupID,
                          bundles: List[ResourceSet], strategy: str) -> bool:
        """Reserve bundle resources (2PC collapsed to one phase on one host).

        Reference: bundle_scheduling_policy.cc (PACK/SPREAD/STRICT_*) and
        placement_group_resource_manager.h prepare/commit.
        """
        alive = [n for n in self.nodes.values() if n.state == "ALIVE"]
        if not alive:
            return False
        placement: List[Node] = []
        if strategy in ("STRICT_PACK",):
            total = ResourceSet()
            for b in bundles:
                total = total + b
            candidates = [n for n in alive if n.resources.can_fit(total)]
            if not candidates:
                return False
            placement = [candidates[0]] * len(bundles)
        else:
            # Greedy per-bundle placement. SPREAD prefers distinct nodes;
            # STRICT_SPREAD requires them.
            used_nodes: List[Node] = []
            for b in bundles:
                # Track tentative usage so multiple bundles on one node
                # don't over-commit.
                def fits(n: Node) -> bool:
                    tentative = b
                    for prev_node, prev_b in zip(placement, bundles):
                        if prev_node is n:
                            tentative = tentative + prev_b
                    return n.resources.can_fit(tentative)

                if strategy == "STRICT_SPREAD":
                    cands = [n for n in alive
                             if n not in used_nodes and fits(n)]
                elif strategy == "SPREAD":
                    cands = sorted(
                        [n for n in alive if fits(n)],
                        key=lambda n: used_nodes.count(n),
                    )
                else:  # PACK
                    cands = sorted(
                        [n for n in alive if fits(n)],
                        key=lambda n: -used_nodes.count(n),
                    )
                if not cands:
                    return False
                placement.append(cands[0])
                used_nodes.append(cands[0])
        states = []
        for node, b in zip(placement, bundles):
            if not node.resources.acquire(b):
                # Roll back.
                for st in states:
                    self.nodes[st.node_id].resources.release(st.resources)
                return False
            states.append(BundleState(resources=b, node_id=node.node_id))
        self.pg_bundles[pg_id] = states
        return True

    def remove_pg(self, pg_id: PlacementGroupID):
        states = self.pg_bundles.pop(pg_id, None)
        if not states:
            return
        for st in states:
            node = self.nodes.get(st.node_id)
            if node and node.state != "DEAD":  # SUSPECT still returns
                node.resources.release(st.resources)

    # ---- lease scheduling ----

    def submit(self, lease: PendingLease):
        self.pending.append(lease)

    def next_lease_id(self) -> str:
        self._lease_counter += 1
        return f"lease-{self._lease_counter}"

    def _pick_node(self, lease: PendingLease) -> Optional[tuple]:
        """Returns (node, pg_id, bundle_index) or None if can't fit now.

        Raises ValueError for permanently infeasible requests.
        """
        strategy = lease.spec.scheduling_strategy
        request = lease.resources
        alive = [n for n in self.nodes.values() if n.state == "ALIVE"]

        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg_id = PlacementGroupID.from_hex(strategy.placement_group_id_hex)
            states = self.pg_bundles.get(pg_id)
            if states is None:
                raise ValueError(f"placement group {pg_id.hex()} not found")
            indices = (
                range(len(states))
                if strategy.bundle_index < 0
                else [strategy.bundle_index]
            )
            for i in indices:
                st = states[i]
                if request.is_subset_of(st.available):
                    node = self.nodes.get(st.node_id)
                    if node and node.state == "ALIVE":
                        return (node, pg_id, i)
            lease.wait_reason = (
                f"waiting on placement group {pg_id.hex()[:8]}: no bundle "
                f"of {len(states)} has {request.to_dict()} free (bundle "
                f"nodes may be SUSPECT/DEAD or capacity in use)")
            return None

        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            node = self.nodes.get(NodeID.from_hex(strategy.node_id_hex))
            if node is None or node.state != "ALIVE":
                if strategy.soft:
                    pass  # fall through to default policy
                else:
                    raise ValueError("affinity node not found")
            elif node.resources.can_fit(request):
                return (node, None, -1)
            elif not strategy.soft:
                if node.resources.feasible(request):
                    lease.wait_reason = (
                        f"affinity node {strategy.node_id_hex[:8]} busy: "
                        f"{request.to_dict()} not free now (available "
                        f"{node.resources.available.to_dict()})")
                    return None
                raise ValueError("affinity node cannot ever fit request")

        feasible = [n for n in alive if n.resources.feasible(request)]
        if not feasible:
            raise ValueError(
                f"request {request.to_dict()} is infeasible on all nodes"
            )
        fitting = [n for n in feasible if n.resources.can_fit(request)]
        if not fitting:
            lease.wait_reason = (
                f"waiting for resources {request.to_dict()}: feasible on "
                f"{len(feasible)}/{len(alive)} alive node(s), none has "
                f"them free now")
            return None

        if isinstance(strategy, SpreadSchedulingStrategy):
            self._spread_rr += 1
            return (fitting[self._spread_rr % len(fitting)], None, -1)

        # Hybrid policy (reference: hybrid_scheduling_policy.cc): prefer the
        # first (local) node while its critical utilization is below the
        # threshold, otherwise pick the least-utilized fitting node.
        first = fitting[0]
        if first.resources.utilization() < self.spread_threshold:
            return (first, None, -1)
        best = min(fitting, key=lambda n: n.resources.utilization())
        return (best, None, -1)

    def pump(self) -> List[tuple]:
        """Try to grant pending leases.

        Returns a list of (lease, node, pg_id, bundle_index, idle_worker)
        grants; idle_worker may be None, in which case the caller must spawn
        a worker on that node and complete the grant on registration.
        """
        from ray_tpu.util import flight_recorder

        grants = []
        remaining = []
        for lease in self.pending:
            if lease.future.done():
                continue  # cancelled
            # PG-scheduled leases tag their placement group so the
            # `why placement-group` explainer can find this evidence
            # by id, not by substring luck.
            pg_hex = getattr(lease.spec.scheduling_strategy,
                             "placement_group_id_hex", None)
            try:
                picked = self._pick_node(lease)
            except ValueError as e:
                flight_recorder.record(
                    "sched", "lease_infeasible", severity="error",
                    task=lease.spec.task_id.hex()[:16],
                    name=lease.spec.name, reason=str(e),
                    pg=pg_hex[:16] if pg_hex else "")
                lease.future.set_exception(e)
                continue
            if picked is None:
                if lease.wait_reason != lease._reason_recorded:
                    # Only reason CHANGES hit the ring — a parked lease
                    # must not spam an entry per 0.2s pump tick.
                    lease._reason_recorded = lease.wait_reason
                    flight_recorder.record(
                        "sched", "lease_wait", severity="warn",
                        task=lease.spec.task_id.hex()[:16],
                        name=lease.spec.name, reason=lease.wait_reason,
                        pg=pg_hex[:16] if pg_hex else "")
                remaining.append(lease)
                continue
            node, pg_id, bundle_index = picked
            if pg_id is not None:
                st = self.pg_bundles[pg_id][bundle_index]
                st.available = st.available - lease.resources
            else:
                node.resources.acquire(lease.resources)
            idle_worker = self.pool.pop_idle(node.node_id)
            grants.append((lease, node, pg_id, bundle_index, idle_worker))
        self.pending = remaining
        from ray_tpu.util import telemetry

        if grants:
            telemetry.inc("ray_tpu_scheduler_leases_granted_total",
                          len(grants))
            now = time.monotonic()
            for lease, node, *_rest in grants:
                telemetry.observe(
                    "ray_tpu_scheduler_placement_latency_seconds",
                    max(0.0, now - lease.queued_at))
                flight_recorder.record(
                    "sched", "lease_granted",
                    task=lease.spec.task_id.hex()[:16],
                    name=lease.spec.name, node=node.node_id.hex()[:12],
                    waited_s=round(now - lease.queued_at, 4))
        telemetry.set_gauge("ray_tpu_scheduler_pending_leases",
                            len(remaining))
        return grants

    def record_lease(self, lease_id: str, node_id: NodeID,
                     resources: ResourceSet, pg_id, bundle_index: int):
        self.active_leases[lease_id] = (node_id, resources, pg_id, bundle_index)

    def release_lease(self, lease_id: str):
        entry = self.active_leases.pop(lease_id, None)
        if entry is None:
            return
        node_id, resources, pg_id, bundle_index = entry
        if pg_id is not None:
            states = self.pg_bundles.get(pg_id)
            if states is not None:
                states[bundle_index].available = (
                    states[bundle_index].available + resources
                )
            return
        node = self.nodes.get(node_id)
        # != DEAD: a lease finishing while the node is SUSPECT (agent in
        # its death-grace window) must still return capacity — skipping
        # it would leak those units permanently once the agent
        # reattaches.
        if node and node.state != "DEAD":
            node.resources.release(resources)

    # ---- introspection ----

    def cluster_resources(self) -> Dict[str, float]:
        total = ResourceSet()
        for n in self.nodes.values():
            if n.state == "ALIVE":
                total = total + n.resources.total
        return total.to_dict()

    def available_resources(self) -> Dict[str, float]:
        avail = ResourceSet()
        for n in self.nodes.values():
            if n.state == "ALIVE":
                avail = avail + n.resources.available
        return avail.to_dict()
