"""MPI runtime-env plugin: run a task's function inside an MPI gang.

Reference: python/ray/_private/runtime_env/mpi.py:41 (MPIPlugin wraps
the worker command in ``mpirun``; rank 0 becomes the Ray worker while
ranks > 0 run the user's ``worker_entry``). Redesigned for this
runtime's single-process task executor: instead of rewriting the
long-lived worker's own command line, a task whose runtime_env carries
``mpi`` executes its function inside a freshly launched process gang —

    @ray_tpu.remote
    def dist_compute(...): ...
    dist_compute.options(runtime_env={"mpi": {
        "args": ["-n", "4"],
        "worker_entry": "my_pkg.mpi_worker",   # ranks > 0 run this
    }}).remote(...)

The function + arguments ship to the gang via a pickle spool file;
every rank first imports/calls ``worker_entry(rank, size)`` (host
bootstrap — typically a loop that serves MPI collectives), rank 0 then
runs the task body, and its return value (or pickled exception) comes
back through the spool. The launcher is ``mpirun`` by default; the
built-in ``"simulated"`` launcher spawns the gang as plain subprocesses
with RTPU_MPI_RANK/SIZE set, which is what CI images without an MPI
distribution (like this one) exercise — see PARITY.md.
"""

from __future__ import annotations

import os
import pickle
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict

from ray_tpu import exceptions as exc


def _detect_rank_size() -> tuple:
    """Rank/size from whatever launcher started us (OpenMPI, MPICH/
    Hydra, or the built-in simulator)."""
    for rank_var, size_var in (
            ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
            ("PMI_RANK", "PMI_SIZE"),
            ("RTPU_MPI_RANK", "RTPU_MPI_SIZE")):
        if rank_var in os.environ:
            return int(os.environ[rank_var]), int(os.environ[size_var])
    return 0, 1


def _parse_np(args) -> int:
    """Extract the gang size from mpirun-style args (-n/-np N)."""
    args = list(args or [])
    for flag in ("-n", "-np", "--np"):
        if flag in args:
            idx = args.index(flag)
            if idx + 1 >= len(args):
                raise exc.RuntimeEnvSetupError(
                    f"mpi args {args!r}: {flag} needs a rank count")
            try:
                return int(args[idx + 1])
            except ValueError:
                raise exc.RuntimeEnvSetupError(
                    f"mpi args {args!r}: {flag} value is not an int")
    return 1


def run_under_mpi(mpi_cfg: Dict[str, Any], fn, args, kwargs) -> Any:
    """Execute ``fn(*args, **kwargs)`` on rank 0 of an MPI gang and
    return its result. Raises RuntimeEnvSetupError if no launcher is
    available, or re-raises the task's own exception."""
    import cloudpickle

    launcher = mpi_cfg.get("launcher", "mpirun")
    mpi_args = list(mpi_cfg.get("args") or [])
    worker_entry = mpi_cfg.get("worker_entry")
    spool = tempfile.mkdtemp(prefix="rtpu_mpi_")
    payload = os.path.join(spool, "payload.pkl")
    result_path = os.path.join(spool, "result.pkl")
    try:
        with open(payload, "wb") as f:
            cloudpickle.dump(
                {"fn": fn, "args": args, "kwargs": kwargs,
                 "worker_entry": worker_entry}, f)
        child = [sys.executable, "-m", "ray_tpu.core.runtime_env_mpi",
                 payload, result_path]
        # Gang ranks are fresh interpreters: make sure they can import
        # ray_tpu regardless of the worker's own sys.path bootstrap.
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(
                                 os.pathsep)
        if launcher == "simulated":
            procs = _launch_simulated(_parse_np(mpi_args), child, env)
            deadline = time.monotonic() + mpi_cfg.get("timeout", 600)
            try:
                # Rank 0 carries the result; ranks > 0 run worker_entry
                # loops that commonly never return on their own (they
                # serve collectives) — like mpirun tearing the job down
                # when the program ends, the gang dies with rank 0.
                rc0 = procs[0].wait(
                    timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for p in procs:
                    p.wait(timeout=10)
                raise exc.RayTpuError(
                    "MPI gang timed out; all ranks killed")
            # Grace for ranks that exit on their own, then tear down.
            grace_until = time.monotonic() + min(
                5.0, max(0.1, deadline - time.monotonic()))
            for p in procs[1:]:
                try:
                    p.wait(timeout=max(
                        0.1, grace_until - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.terminate()
            for p in procs[1:]:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            bad = [rc0] if rc0 != 0 else []
        else:
            if shutil.which(launcher) is None:
                raise exc.RuntimeEnvSetupError(
                    f"MPI launcher {launcher!r} not found on this host; "
                    "install an MPI distribution or use "
                    '{"launcher": "simulated"}')
            rc = subprocess.run(
                [launcher, *mpi_args, *child], env=env,
                timeout=mpi_cfg.get("timeout", 600)).returncode
            bad = [rc] if rc != 0 else []
        if not os.path.exists(result_path):
            raise exc.RayTpuError(
                f"MPI gang produced no result (exit codes {bad or 'ok'})")
        with open(result_path, "rb") as f:
            out = pickle.load(f)
        if "err" in out:
            raise exc.RayTpuError(
                f"MPI task failed on rank 0:\n{out['err']}")
        if bad:
            raise exc.RayTpuError(
                f"MPI ranks exited nonzero: {bad}")
        return out["ok"]
    finally:
        shutil.rmtree(spool, ignore_errors=True)


def _launch_simulated(n: int, child_cmd, base_env) -> list:
    """The built-in launcher: N plain subprocesses with rank/size env
    (no MPI distribution required; collectives must come from the
    user's own rendezvous, e.g. jax.distributed or sockets)."""
    procs = []
    for rank in range(n):
        env = dict(base_env)
        env["RTPU_MPI_RANK"] = str(rank)
        env["RTPU_MPI_SIZE"] = str(n)
        procs.append(subprocess.Popen(child_cmd, env=env))
    return procs


def _child_main(payload_path: str, result_path: str) -> int:
    import importlib
    import traceback

    import cloudpickle

    rank, size = _detect_rank_size()
    with open(payload_path, "rb") as f:
        payload = cloudpickle.load(f)
    entry = payload.get("worker_entry")
    entry_fn = None
    if entry:
        mod, _, name = entry.rpartition(".")
        entry_fn = getattr(importlib.import_module(mod), name)
    if rank != 0:
        # Non-zero ranks ARE the MPI workers: worker_entry runs the
        # user's collective-serving loop (reference: MPIPlugin's
        # worker_entry contract).
        if entry_fn is not None:
            entry_fn(rank, size)
        return 0
    ok = False
    try:
        if entry_fn is not None:
            entry_fn(rank, size)
        value = payload["fn"](*payload["args"], **payload["kwargs"])
        # cloudpickle: return values may be instances of driver-defined
        # classes that stdlib pickle cannot serialize by reference —
        # and serialization failure must surface as an error blob, not
        # crash the child after a "successful" run.
        blob = cloudpickle.dumps({"ok": value})
        ok = True
    except BaseException:
        blob = cloudpickle.dumps({"err": traceback.format_exc()})
    tmp = result_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, result_path)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1], sys.argv[2]))
