"""Node agent — the per-host daemon that joins a remote machine to the
cluster.

Reference mapping: the raylet's node-manager role (src/ray/raylet/
node_manager.cc) minus scheduling, which stays central in this topology:

- registers the host with the head (``register_node``) and holds the
  connection open as the health channel (close ⇒ node death),
- forks/pools worker processes on this host at the head's request
  (worker_pool.h:156 analog; the head's WorkerPool delegates via its
  spawn_remote hook),
- owns this host's shared-memory object arena and serves cross-node
  object pulls from it (object_manager.cc chunk reads),
- reaps worker processes that die before registering and reports them
  (``worker_exited_early``) so the head's backoff/respawn logic applies.

Run on each additional host:

    python -m ray_tpu.core.node_agent --head-host <ip> --head-port <p> \
        --num-cpus 8 [--host <this-host-ip>]

The test substrate runs several agents on one machine with distinct shm
namespaces, which exercises the full cross-node protocol (distinct
stores, network pulls) without needing two machines.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional

from ray_tpu.core import native_store, object_store, object_transfer, retry, rpc
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID, WorkerID

logger = logging.getLogger(__name__)


def _swallow(site: str, error: BaseException, **tags) -> None:
    """Evidence for intentionally-dropped errors (silent-except audit):
    ride the flight recorder (guard/swallowed) so ``debug dump`` on
    this agent can explain them later."""
    from ray_tpu.util import flight_recorder

    flight_recorder.swallow(site, error, **tags)


class NodeAgent:
    def __init__(self, head_host: str, head_port: int,
                 resources: Dict[str, float], host: str = "127.0.0.1",
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: Optional[int] = None):
        self.head_host = head_host
        self.head_port = head_port
        self.host = host
        self.resources = resources
        self.labels = labels or {}
        self.session_dir = _make_session_dir()
        self.node_id_hex: Optional[str] = None
        self.server: Optional[rpc.Server] = None
        self.port: Optional[int] = None
        self.head_conn: Optional[rpc.Connection] = None
        self._procs: Dict[str, subprocess.Popen] = {}
        self._forkserver = None  # lazily started ForkserverClient
        self._exit = asyncio.Event()
        self._peer_conns: Dict[tuple, rpc.Connection] = {}
        self._puller = object_transfer.ObjectPuller(self._get_peer_conn)
        # Unified retry envelope for agent->head control calls.
        self._retry = retry.RetryPolicy.from_config(get_config())
        self._reconnecting = False

        capacity = object_store_memory or object_store.default_capacity(
            get_config().object_store_memory_proportion)
        name = f"rtpu_arena_{os.getpid()}_{int(time.time())}"
        self.arena = native_store.NativeArena.create(name, capacity)
        self.arena_name = name if self.arena is not None else None
        if self.arena is not None:
            native_store.set_attached_arena(self.arena)
            os.environ["RAY_TPU_ARENA"] = name
        else:
            # Never fall back to an inherited arena: per-node store
            # isolation is the point of the agent.
            native_store.set_attached_arena(None)
            os.environ.pop("RAY_TPU_ARENA", None)
        # Workers must spill to this host's disk, not the head's path.
        os.environ["RAY_TPU_SESSION_DIR"] = self.session_dir
        if not resources.get("TPU"):
            # Same policy as the head node (core/node.py): chip-less
            # workers don't load accelerator site hooks.
            os.environ.setdefault("RAY_TPU_WORKER_PYTHONPATH_EXCLUDE",
                                  "axon_site")

    # ---- rpc handlers ----

    def handlers(self) -> dict:
        return {
            "spawn_worker": self.h_spawn_worker,
            "kill_worker": self.h_kill_worker,
            "free_objects": self.h_free_objects,
            "ping": self.h_ping,
            "pull_object": self.h_pull_object,
            "shutdown_node": self.h_shutdown_node,
            "debug_dump": self.h_debug_dump,
            "profile_capture": self.h_profile_capture,
            "device_trace_capture": self.h_device_trace_capture,
            **object_transfer.serve_handlers(),
        }

    async def h_debug_dump(self, conn, payload):
        """The agent's slice of the cluster debug plane: its own
        flight-recorder ring + all-thread stacks."""
        payload = payload or {}
        from ray_tpu.util import flight_recorder

        out = {
            "pid": os.getpid(),
            "node_id": self.node_id_hex,
            "mode": "agent",
            "ts": time.time(),
            "stacks": (flight_recorder.dump_stacks()
                       if payload.get("include_stacks", True) else {}),
        }
        if payload.get("include_events", True):
            out["events"] = flight_recorder.snapshot(
                limit=payload.get("event_limit"))
        return out

    async def h_profile_capture(self, conn, payload):
        """The agent's slice of the live profiling plane: sample its
        own threads (pull pump, log tailer, health channel) off-loop."""
        payload = payload or {}
        from ray_tpu.util import profiler

        duration = float(payload.get("duration_s", 5.0))
        hz = float(payload.get("hz", 100.0))
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: profiler.capture(duration, hz))
        out.update(mode="agent", node_id=self.node_id_hex)
        return out

    async def h_device_trace_capture(self, conn, payload):
        """The agent's slice of the device-trace plane. Agents rarely
        touch a device, but the capture still yields the host-lane
        sampler sweep and (on shared-backend nodes) any device activity
        the agent process itself drives — and a uniform surface keeps
        the ``kind=all`` fan-out simple."""
        payload = payload or {}
        from ray_tpu.util import device_trace

        duration = float(payload.get("duration_s", 2.0))
        out = await asyncio.get_running_loop().run_in_executor(
            None, lambda: device_trace.capture(duration))
        out.update(mode="agent", node_id=self.node_id_hex)
        return out

    async def h_pull_object(self, conn, payload):
        """Workers delegate cross-node pulls here (reference: the
        raylet's pull manager does the pulling, workers read shm):
        concurrent worker requests for one object coalesce on the
        agent's single puller, and the long-lived agent's arena extents
        get recycled, so steady-state ingests land on warm pages."""
        from ray_tpu.core.ids import ObjectID as _OID

        object_id = _OID.from_hex(payload["object_id"])
        locations = [tuple(a) for a in payload.get("locations", [])]
        try:
            ok = await self._puller.pull(object_id, locations)
        except Exception as e:  # noqa: BLE001
            logger.info("agent pull of %s failed: %s",
                        payload["object_id"][:12], e)
            ok = False
        return {"ok": bool(ok)}

    async def _get_peer_conn(self, address):
        conn = self._peer_conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        new = await rpc.connect(address[0], address[1], {})
        # Re-check after the await: a concurrent pull may have connected
        # first — keep one connection per peer, close the loser.
        cur = self._peer_conns.get(address)
        if cur is not None and not cur.closed:
            await new.close()
            return cur
        self._peer_conns[address] = new
        return new

    async def h_ping(self, conn, payload):
        return {"ok": True, "node_id": self.node_id_hex}

    async def h_spawn_worker(self, conn, payload):
        worker_id = payload["worker_id"]
        env = dict(os.environ)
        env["RAY_TPU_HEAD_HOST"] = self.head_host
        env["RAY_TPU_HEAD_PORT"] = str(self.head_port)
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_NODE_ID"] = self.node_id_hex or ""
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_ADVERTISE_HOST"] = self.host
        # Workers delegate cross-node pulls to this agent (h_pull_object).
        env["RAY_TPU_AGENT_HOST"] = "127.0.0.1"
        env["RAY_TPU_AGENT_PORT"] = str(self.port)
        env["RAY_TPU_BIND_HOST"] = "0.0.0.0" if self.host not in (
            "127.0.0.1", "localhost") else "127.0.0.1"
        if self.arena_name:
            env["RAY_TPU_ARENA"] = self.arena_name
        else:
            env.pop("RAY_TPU_ARENA", None)
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(ray_tpu.__file__))
        existing = env.get("PYTHONPATH", "")
        parts = [pkg_root] + (existing.split(os.pathsep) if existing
                              else [])
        from ray_tpu.core.scheduler import (
            apply_worker_bytecode_cache,
            filter_worker_pythonpath,
        )

        env["PYTHONPATH"] = os.pathsep.join(
            filter_worker_pythonpath(parts))
        apply_worker_bytecode_cache(env)
        log_path = os.path.join(self.session_dir, "logs",
                                f"worker-{worker_id[:12]}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        proc = None
        from ray_tpu.core.config import get_config

        if os.name == "posix" and get_config().worker_forkserver:
            try:
                if self._forkserver is None:
                    from ray_tpu.core.forkserver import ForkserverClient

                    self._forkserver = ForkserverClient(
                        self.session_dir, env)
                # The spawn blocks on the forkserver socket; first call
                # pays the preimport (~2.5 s), later ones are ms-scale.
                # Run in a thread to keep the agent's event loop live.
                import asyncio

                proc = await asyncio.get_running_loop().run_in_executor(
                    None, self._forkserver.spawn, env, log_path)
            except Exception:
                logger.warning("agent forkserver spawn failed; cold "
                               "start", exc_info=True)
                proc = None
        if proc is None:
            with open(log_path, "ab") as log_file:
                proc = subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu.core.worker_main"],
                    env=env, stdout=log_file, stderr=subprocess.STDOUT,
                    start_new_session=True,
                )
        self._procs[worker_id] = proc
        return {"ok": True, "pid": proc.pid}

    async def h_kill_worker(self, conn, payload):
        proc = self._procs.pop(payload["worker_id"], None)
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except Exception:  # lint: allow-silent(best-effort kill; the worker is already exiting)
                pass
        return {"ok": True}

    async def h_free_objects(self, conn, payload):
        for hex_id in payload["object_ids"]:
            if self.arena is not None:
                self.arena.delete(ObjectID.from_hex(hex_id).binary())
            else:
                # Python fallback store: objects live as per-object shm
                # segments that nothing else on this host will unlink.
                object_store._unlink_segment(hex_id)
            object_store.spill_delete(ObjectID.from_hex(hex_id))
        return {"ok": True}

    async def h_shutdown_node(self, conn, payload):
        self._exit.set()
        return {"ok": True}

    # ---- lifecycle ----

    async def start(self):
        # Event-loop lag probe (control-plane observatory): the agent's
        # loop serves worker spawns and object pulls for its node.
        try:
            from ray_tpu.util import rpc_stats

            rpc_stats.install_probe(asyncio.get_running_loop(),
                                    "node-agent")
        except Exception:  # lint: allow-silent(lag probe is decoration; the agent must boot regardless)
            pass
        self.server = rpc.Server(self.handlers(), name="node-agent")
        bind = "0.0.0.0" if self.host not in ("127.0.0.1",
                                              "localhost") else "127.0.0.1"
        # The data-plane listener (and spawned workers') bind policy
        # follows the control plane's.
        os.environ.setdefault("RAY_TPU_BIND_HOST", bind)
        self.port = await self.server.start(bind, 0)
        # Head may still be coming up: dial under the unified policy.
        await self._retry.execute(
            lambda: self._dial_head(reconnect=False),
            label="register_node")
        logger.info("node %s registered (%s:%s), %s",
                    self.node_id_hex[:12], self.host, self.port,
                    self.resources)
        asyncio.get_running_loop().create_task(self._reap_loop())

    async def _dial_head(self, reconnect: bool) -> None:
        """Dial the head and (re)register. On a reconnect the payload
        carries our node id so the head reattaches us to the SUSPECT
        node inside its death-grace window instead of minting a new
        one."""
        conn = await rpc.connect(
            self.head_host, self.head_port, self.handlers(),
            name="agent-head")
        payload = {
            "host": self.host,
            "port": self.port,
            "resources": self.resources,
            "labels": self.labels,
        }
        if reconnect and self.node_id_hex:
            payload["node_id"] = self.node_id_hex
        try:
            reply = await conn.call("register_node", payload, timeout=10.0)
        except BaseException:
            await conn.close()
            raise
        if not reply.get("ok"):
            await conn.close()
            raise RuntimeError(f"node registration rejected: {reply}")
        conn.on_close = self._on_head_conn_lost
        if conn.closed:
            # Torn down between the reply and the hook install (the
            # close callback fired with on_close still unset): surface
            # it to the surrounding retry so the dial is repeated —
            # silently keeping a dead head_conn makes a zombie agent.
            raise rpc.ConnectionLost("head closed during registration")
        self.head_conn = conn
        if (reconnect and self.node_id_hex
                and reply["node_id"] != self.node_id_hex):
            # Grace expired head-side: we came back as a brand-new node.
            # Workers of the old identity are unreachable from the head
            # (it already restarted their actors elsewhere) — letting
            # them run would double-execute side effects and double-book
            # this host's resources.
            logger.warning(
                "re-registered as new node %s (was %s); killing %d "
                "workers of the dead identity", reply["node_id"][:12],
                self.node_id_hex[:12], len(self._procs))
            self._kill_all_workers()
        self.node_id_hex = reply["node_id"]

    def _on_head_conn_lost(self, conn):
        if self._exit.is_set() or self._reconnecting:
            return
        self._reconnecting = True
        logger.warning("head connection lost; reconnecting with backoff")
        asyncio.get_running_loop().create_task(self._reconnect_head())

    async def _reconnect_head(self):
        # Enough attempts to comfortably outlast the head's
        # gcs_node_death_grace_s (reconnect inside the window keeps our
        # node id, workers and store intact).
        policy = retry.RetryPolicy.from_config(
            get_config(), max_attempts=10, base_delay_s=0.25,
            max_delay_s=2.0)
        try:
            await policy.execute(
                lambda: self._dial_head(reconnect=True),
                label="agent reconnect")
            logger.info("reconnected to head as node %s",
                        (self.node_id_hex or "")[:12])
        except Exception:
            logger.error(
                "head unreachable after %d attempts; shutting down "
                "node agent", policy.max_attempts)
            self._exit.set()
        finally:
            self._reconnecting = False

    async def _reap_loop(self):
        from ray_tpu.core import memory_monitor as mm
        from ray_tpu.core.log_monitor import LogTailer

        tailer = LogTailer(os.path.join(self.session_dir, "logs"))
        config = get_config()
        monitor = None
        if config.memory_monitor_enabled:
            monitor = mm.MemoryMonitor(
                threshold=config.memory_usage_threshold,
                candidates=lambda: [
                    mm.VictimCandidate(
                        worker_id_hex=wid, pid=proc.pid,
                        # The agent doesn't see task specs; the head's
                        # retry machinery decides survivability. Rank by
                        # recency only.
                        retriable=True, is_actor=False,
                        started_at=0.0)
                    for wid, proc in self._procs.items()
                    if proc.poll() is None
                ],
                kill=self._oom_kill)
        while not self._exit.is_set():
            for worker_id, proc in list(self._procs.items()):
                if proc.poll() is not None:
                    self._procs.pop(worker_id, None)
                    try:
                        # Idempotent at the head (no-op unless the worker
                        # is still STARTING) — safe to replay through a
                        # blip on the health channel.
                        await self._retry.execute(
                            lambda wid=worker_id: self.head_conn.call(
                                "worker_exited_early",
                                {"worker_id": wid}),
                            timeout_per_attempt=10.0,
                            label="worker_exited_early")
                    except Exception as e:
                        # The head now learns of the exit only from the
                        # worker's connection close — slower backoff
                        # bookkeeping, worth a recorded trace.
                        _swallow("agent.worker_exited_early", e,
                                 worker=worker_id[:16])
            # Stream new worker output to subscribed drivers
            # (reference: log_monitor.py publishing to GCS pubsub).
            entries = tailer.poll()
            if entries:
                try:
                    await self.head_conn.call("publish", {
                        "channel": "worker_logs",
                        "data": {"node": self.node_id_hex or "",
                                 "entries": entries},
                    })
                except Exception as e:
                    _swallow("agent.worker_log_publish", e,
                             dropped=len(entries))
            if monitor is not None:
                try:
                    killed = monitor.maybe_kill()
                except Exception:
                    logger.exception("memory monitor poll failed")
                    killed = None
                if killed is not None:
                    reason = self._last_oom_reason or "memory monitor kill"
                    try:
                        # Idempotent (overwrites the same reason row).
                        await self._retry.execute(
                            lambda: self.head_conn.call(
                                "report_oom_kill",
                                {"worker_id": killed, "reason": reason}),
                            timeout_per_attempt=10.0,
                            label="report_oom_kill")
                    except Exception as e:
                        _swallow("agent.report_oom_kill", e,
                                 worker=str(killed)[:16])
            await asyncio.sleep(0.5)

    _last_oom_reason: Optional[str] = None

    def _oom_kill(self, victim, reason: str):
        self._last_oom_reason = reason
        # The proc stays in _procs: the reap loop must observe the exit
        # and send worker_exited_early so the head's agent-exit
        # bookkeeping (spawn backoff, grant cleanup) fires for OOM
        # victims too — popping here would leave only the RPC
        # connection-close signal.
        proc = self._procs.get(victim.worker_id_hex)
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except Exception:  # lint: allow-silent(best-effort OOM kill; reap loop reports the exit either way)
                pass

    async def run_forever(self):
        await self._exit.wait()
        self.shutdown()

    def _kill_all_workers(self):
        for proc in self._procs.values():
            if proc.poll() is None:
                try:
                    proc.kill()
                except Exception:  # lint: allow-silent(best-effort kill during agent shutdown)
                    pass
        self._procs.clear()

    def shutdown(self):
        self._kill_all_workers()
        if self._forkserver is not None:
            self._forkserver.stop()
            self._forkserver = None
        if self.arena is not None:
            native_store.set_attached_arena(None)
            self.arena.destroy()
            self.arena = None


def _make_session_dir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    os.makedirs(base, exist_ok=True)
    path = os.path.join(
        base, f"node_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}")
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    return path


async def _amain(args) -> int:
    resources = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    if args.memory:
        resources["memory"] = float(args.memory)
    if args.resources:
        import json

        resources.update({k: float(v)
                          for k, v in json.loads(args.resources).items()})
    agent = NodeAgent(
        head_host=args.head_host, head_port=args.head_port,
        resources=resources, host=args.host,
        object_store_memory=args.object_store_memory,
    )
    await agent.start()
    await agent.run_forever()
    return 0


def main():
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s agent %(name)s: %(message)s",
    )
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--head-host", required=True)
    p.add_argument("--head-port", type=int, required=True)
    p.add_argument("--num-cpus", type=float, default=os.cpu_count() or 1)
    p.add_argument("--num-tpus", type=float, default=0)
    p.add_argument("--memory", type=float, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--resources", default=None,
                   help='extra custom resources as JSON, e.g. \'{"hostB":1}\'')
    args = p.parse_args()
    from ray_tpu.util import flight_recorder, profiler

    flight_recorder.install_crash_handler()
    profiler.maybe_start_continuous()
    try:
        code = asyncio.run(_amain(args))
    except KeyboardInterrupt:
        code = 0
    except BaseException as e:  # crashed agent loop: leave evidence
        flight_recorder.flush_postmortem(f"{type(e).__name__}: {e}")
        raise
    os._exit(code or 0)


if __name__ == "__main__":
    main()
