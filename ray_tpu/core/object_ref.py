"""ObjectRef — a future-like handle to a task return or put object.

Reference: python/ray/_raylet.pyx ObjectRef + the ownership model of
src/ray/core_worker/reference_count.h: every object has an **owner** (the
worker that created it); other holders are **borrowers**. Refs embed the
owner's address so borrowers can fetch the value and report reference
removal directly to the owner.

Pickling an ObjectRef (e.g. inside task args) produces a borrowed ref on
the consumer side; creation/destruction of refs drives the distributed
reference count through the process-local CoreWorker (set via
``set_core_worker``).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.task_spec import Address

# Process-local CoreWorker used by refs for get/refcount traffic.
_core_worker = None


def set_core_worker(cw):
    global _core_worker
    _core_worker = cw


def get_core_worker():
    return _core_worker


class ObjectRef:
    __slots__ = ("_id", "_owner", "_is_owned", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[Address] = None,
                 is_owned: bool = False, skip_adding_local_ref: bool = False):
        self._id = object_id
        self._owner = owner
        self._is_owned = is_owned
        if not skip_adding_local_ref and _core_worker is not None:
            _core_worker.reference_counter.add_local_ref(self)

    @property
    def id(self) -> ObjectID:
        return self._id

    @property
    def owner_address(self) -> Optional[Address]:
        return self._owner

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def is_nil(self) -> bool:
        return self._id.is_nil()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        if _core_worker is None:
            raise RuntimeError("ray_tpu not initialized")
        return _core_worker.as_future(self)

    def __reduce__(self):
        owner = (
            (self._owner.host, self._owner.port, self._owner.worker_id_hex)
            if self._owner
            else None
        )
        if _core_worker is not None:
            _core_worker.reference_counter.on_ref_serialized(self)
        return (_rebuild_ref, (self._id.binary(), owner))

    def __del__(self):
        if _core_worker is not None:
            try:
                _core_worker.reference_counter.remove_local_ref(self)
            except Exception:
                pass

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    # Allow `await ref` inside async actors.
    def __await__(self):
        if _core_worker is None:
            raise RuntimeError("ray_tpu not initialized")
        return _core_worker.get_async(self).__await__()


def _rebuild_ref(id_bytes: bytes, owner: Optional[tuple]) -> ObjectRef:
    address = Address(owner[0], owner[1], owner[2]) if owner else None
    # Normal construction: registers a local ref whose destruction sends
    # remove_ref to the owner — the -1 matching the serializer's +1 borrow.
    return ObjectRef(ObjectID(id_bytes), address, is_owned=False)
