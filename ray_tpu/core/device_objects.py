"""Device-native object plane: sharded ``jax.Array``s without host bounces.

Reference gap (SURVEY §7.3 hard-part #3, ROADMAP open item #1): the host
object plane converts every ``jax.Array`` to numpy before pickling
(core/serialization.py), so a sharded model's weights round-trip host RAM
on every handoff.  This module keeps device arrays ON DEVICE:

- ``put`` detects qualifying ``jax.Array`` leaves (fully-addressable
  ``NamedSharding``/``SingleDeviceSharding``), registers their per-shard
  device buffers in a process-local registry, and serializes only a tiny
  envelope containing ``DeviceLeafRef`` placeholders plus a sharding
  descriptor (mesh axes/shape, partition spec, dtype/shape, per-shard
  layout — the pjit/GSPMD model of arxiv 2204.06514 made these first-class
  metadata, so they can be stored and re-materialized).
- ``get`` in the producing process returns the original array BY
  REFERENCE — zero copies of any kind.
- ``get`` in another process pulls shard-by-shard from any registered
  holder (resumable range reads over the bulk data plane, chunked-RPC
  fallback) and lands each shard through ``jax.device_put`` against the
  recorded sharding: host staging is bounded by a few shards, never the
  whole array.  Consumers register as holders, so a cold-starting Serve
  replica pulls weights from the nearest peer replica instead of the
  original producer (weight delivery at serve scale — arxiv 2605.25645
  measures exactly this cold-start cost).
- ``donate=True`` on transfer deletes the source holder's device buffers
  once the consumer has them — a move, not a copy, of HBM.

Everything degrades to the host path: non-jax values, exotic shardings,
or a disabled plane (``device_object_plane_enabled=False``) use the
numpy route unchanged.  Under ``JAX_PLATFORMS=cpu`` the same per-shard
protocol runs against CPU devices, which is what tier-1 exercises.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core.ids import OBJECT_ID_SIZE, ObjectID

# Descriptor kinds.
KIND_NAMED = "named"
KIND_SINGLE = "single"

#: Envelopes below this are mirrored into the head's owner table next to
#: the location entry, so holders can serve the object after the owner
#: dies (replica cold-start-from-peer). Larger envelopes (device arrays
#: mixed with big host data) stay owner-only.
MANIFEST_ENVELOPE_CAP = 4 << 20


class DeviceLeafRef:
    """Placeholder pickled into the envelope where a device array was.

    Carries everything a consumer needs to rebuild the leaf: the owning
    object id, the leaf's position, and the full sharding descriptor —
    so resolution never depends on reaching the producer for metadata.
    """

    __slots__ = ("obj_hex", "leaf", "desc")

    def __init__(self, obj_hex: str, leaf: int, desc: dict):
        self.obj_hex = obj_hex
        self.leaf = leaf
        self.desc = desc

    def __reduce__(self):
        return (DeviceLeafRef, (self.obj_hex, self.leaf, self.desc))

    def __repr__(self):
        return (f"DeviceLeafRef({self.obj_hex[:12]}…/{self.leaf}, "
                f"{self.desc.get('kind')}, shape="
                f"{tuple(self.desc.get('global_shape', ()))})")


@dataclass
class _LeafEntry:
    desc: dict
    # The producer keeps the whole array for the zero-copy same-process
    # path; assembled borrower copies keep theirs for peer serving.
    array: Any = None
    # shard key -> single-device jax.Array (one per UNIQUE data piece;
    # replicated shards share a key).
    shards: Dict[int, Any] = field(default_factory=dict)
    nbytes: int = 0


@dataclass
class _ObjectEntry:
    leaves: Dict[int, _LeafEntry] = field(default_factory=dict)
    owned: bool = False
    donated: bool = False


def _make_lock(name: str):
    from ray_tpu.util.locks import make_lock

    return make_lock(name)


_registry_lock = _make_lock("device_objects._registry_lock")
_registry: Dict[str, _ObjectEntry] = {}
# shard id (binary) -> (object hex, leaf, shard key): the serving index
# the data plane and the fetch_device_shard handler look through.
_shard_index: Dict[bytes, Tuple[str, int, int]] = {}

# High-water mark of host bytes staged for shard transfer in this
# process — the "no whole-array host buffer" property is asserted
# against this in tests (peak stays ~shard-sized, not array-sized).
_staging_lock = threading.Lock()
_staging_now = 0
_staging_peak = 0


def _note_staging(delta: int) -> None:
    global _staging_now, _staging_peak
    with _staging_lock:
        _staging_now = max(0, _staging_now + delta)
        if _staging_now > _staging_peak:
            _staging_peak = _staging_now


def peak_staging_bytes() -> int:
    with _staging_lock:
        return _staging_peak


def reset_for_testing() -> None:
    global _staging_now, _staging_peak, _pool_bytes
    with _registry_lock:
        _registry.clear()
        _shard_index.clear()
    with _staging_lock:
        _staging_now = 0
        _staging_peak = 0
    with _pool_lock:
        _pool.clear()
        _pool_bytes = 0


def plane_enabled(config=None) -> bool:
    if config is None:
        from ray_tpu.core.config import get_config

        config = get_config()
    if not config.device_object_plane_enabled:
        return False
    try:
        import jax  # noqa: F401
    except ImportError:
        return False
    return True


# ---------------------------------------------------------------------------
# descriptors
# ---------------------------------------------------------------------------


def _norm_index(index, shape) -> List[List[int]]:
    """A shard's global index (tuple of slices) as [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    # 0-d arrays: index is (), keep it [].
    return out


def _describe(arr) -> Optional[dict]:
    """Sharding descriptor for a qualifying array, else None (host path)."""
    import jax
    from jax.sharding import NamedSharding, SingleDeviceSharding

    if getattr(arr, "is_deleted", lambda: False)():
        return None
    if not arr.is_fully_addressable:
        return None
    sharding = arr.sharding
    desc: dict = {
        "global_shape": [int(d) for d in arr.shape],
        "dtype": str(arr.dtype),
        "nbytes": int(arr.nbytes),
    }
    if isinstance(sharding, SingleDeviceSharding):
        device = next(iter(sharding.device_set))
        desc["kind"] = KIND_SINGLE
        desc["device_id"] = int(device.id)
        shards = [{"key": 0,
                   "index": _norm_index((slice(None),) * arr.ndim,
                                        arr.shape),
                   "shape": [int(d) for d in arr.shape],
                   "nbytes": int(arr.nbytes)}]
    elif isinstance(sharding, NamedSharding):
        mesh = sharding.mesh
        desc["kind"] = KIND_NAMED
        desc["mesh_axes"] = [str(a) for a in mesh.axis_names]
        desc["mesh_shape"] = [int(mesh.shape[a]) for a in mesh.axis_names]
        desc["device_ids"] = [int(d.id)
                              for d in mesh.devices.flat]
        desc["spec"] = _encode_spec(sharding.spec)
        # One entry per UNIQUE data piece: replicated shards share the
        # piece and transfer once per consumer.
        by_index: Dict[tuple, dict] = {}
        for shard in arr.addressable_shards:
            norm = _norm_index(shard.index, arr.shape)
            tkey = tuple(tuple(p) for p in norm)
            if tkey in by_index:
                continue
            data = shard.data
            by_index[tkey] = {
                "key": len(by_index),
                "index": norm,
                "shape": [int(d) for d in data.shape],
                "nbytes": int(data.nbytes),
            }
        shards = sorted(by_index.values(), key=lambda s: s["key"])
    else:
        return None  # Positional/GSPMD/pmap shardings: host path
    desc["shards"] = shards
    return desc


def _encode_spec(spec) -> list:
    """PartitionSpec -> msgpack-able nested list (None | str | [str...])."""
    out = []
    for part in tuple(spec):
        if part is None:
            out.append(None)
        elif isinstance(part, (tuple, list)):
            out.append([str(p) for p in part])
        else:
            out.append(str(part))
    return out


def _decode_spec(encoded):
    from jax.sharding import PartitionSpec as P

    parts = []
    for part in encoded:
        if part is None:
            parts.append(None)
        elif isinstance(part, (tuple, list)):
            parts.append(tuple(part))
        else:
            parts.append(part)
    return P(*parts)


def build_sharding(desc: dict):
    """Rebuild (sharding, device->shard-key map) from a descriptor on
    THIS process's devices. Raises if the local topology can't host the
    mesh (caller falls back to single-device assembly)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh, NamedSharding, SingleDeviceSharding

    if desc["kind"] == KIND_SINGLE:
        by_id = {d.id: d for d in jax.devices()}
        device = by_id.get(desc.get("device_id"), jax.devices()[0])
        sharding = SingleDeviceSharding(device)
        return sharding, {device: 0}
    n = 1
    for dim in desc["mesh_shape"]:
        n *= dim
    local = jax.devices()
    if len(local) < n:
        raise ValueError(
            f"mesh of {n} devices does not fit {len(local)} local devices")
    # Prefer id-identical devices (same-topology consumer); fall back to
    # the first n local devices in order.
    by_id = {d.id: d for d in local}
    wanted = desc.get("device_ids") or []
    if len(wanted) == n and all(i in by_id for i in wanted):
        devs = [by_id[i] for i in wanted]
    else:
        devs = list(local[:n])
    mesh = Mesh(np.array(devs).reshape(desc["mesh_shape"]),
                tuple(desc["mesh_axes"]))
    sharding = NamedSharding(mesh, _decode_spec(desc["spec"]))
    shape = tuple(desc["global_shape"])
    key_by_index = {
        tuple(tuple(p) for p in s["index"]): s["key"]
        for s in desc["shards"]}
    device_keys = {}
    for device, index in sharding.addressable_devices_indices_map(
            shape).items():
        tkey = tuple(tuple(p) for p in _norm_index(index, shape))
        if tkey not in key_by_index:
            raise ValueError("local sharding layout disagrees with the "
                             "recorded shard set")
        device_keys[device] = key_by_index[tkey]
    return sharding, device_keys


def _np_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 etc.  # noqa: F401

        return np.dtype(name)


# ---------------------------------------------------------------------------
# export (producer side of put)
# ---------------------------------------------------------------------------


def shard_id(object_binary: bytes, leaf: int, key: int) -> bytes:
    """Stable pseudo-ObjectID for one shard of one leaf: lets shards ride
    the existing range-read data plane unchanged."""
    h = hashlib.sha1(
        b"devshard:" + object_binary + leaf.to_bytes(4, "little")
        + key.to_bytes(4, "little")).digest()
    return h[:OBJECT_ID_SIZE]


def min_export_bytes(config=None) -> int:
    if config is None:
        from ray_tpu.core.config import get_config

        config = get_config()
    return int(config.device_object_min_bytes)


def export_value(object_id: ObjectID, value: Any,
                 config=None) -> Tuple[Any, int, List[dict]]:
    """Walk ``value``; move qualifying device arrays into the registry.

    Returns (mapped value with DeviceLeafRef placeholders, number of
    leaves exported, leaf descriptors in leaf order)."""
    import jax

    from ray_tpu.core import serialization

    threshold = min_export_bytes(config)
    hex_id = object_id.hex()
    binary = object_id.binary()
    state = {"leaf": 0}
    entry = _ObjectEntry(owned=True)
    descs: List[dict] = []

    def leaf_fn(x):
        if not isinstance(x, jax.Array):
            return serialization.UNCHANGED
        if x.nbytes < threshold:
            return serialization.UNCHANGED  # host path maps it later
        desc = _describe(x)
        if desc is None:
            return serialization.UNCHANGED
        leaf = state["leaf"]
        state["leaf"] += 1
        shards_by_key: Dict[int, Any] = {}
        by_index = {tuple(tuple(p) for p in s["index"]): s["key"]
                    for s in desc["shards"]}
        if desc["kind"] == KIND_SINGLE:
            shards_by_key[0] = x
        else:
            for shard in x.addressable_shards:
                tkey = tuple(tuple(p) for p in
                             _norm_index(shard.index, x.shape))
                key = by_index[tkey]
                if key not in shards_by_key:
                    shards_by_key[key] = shard.data
        entry.leaves[leaf] = _LeafEntry(
            desc=desc, array=x, shards=shards_by_key,
            nbytes=int(desc["nbytes"]))
        descs.append(desc)
        return DeviceLeafRef(hex_id, leaf, desc)

    mapped = serialization.map_tree(value, leaf_fn)
    count = state["leaf"]
    if count:
        with _registry_lock:
            _registry[hex_id] = entry
            for leaf, le in entry.leaves.items():
                for key in le.shards:
                    _shard_index[shard_id(binary, leaf, key)] = (
                        hex_id, leaf, key)
        _report_device_bytes()
    return mapped, count, descs


def register_assembled(object_id: ObjectID, leaf: int, desc: dict,
                       array: Any) -> int:
    """A consumer finished assembling a leaf: become a holder so peers
    can pull from this process (replica cold-start-from-peer path).
    Returns the number of recorded-layout shards this process can now
    serve — 0 when the array was assembled via the single-device
    fallback (its shards don't match the descriptor, so advertising
    this process as a holder would be a lie)."""
    import jax

    hex_id = object_id.hex()
    binary = object_id.binary()
    shards_by_key: Dict[int, Any] = {}
    if desc["kind"] == KIND_SINGLE:
        shards_by_key[0] = array
    else:
        by_index = {tuple(tuple(p) for p in s["index"]): s["key"]
                    for s in desc["shards"]}
        for shard in array.addressable_shards:
            tkey = tuple(tuple(p) for p in
                         _norm_index(shard.index, array.shape))
            key = by_index.get(tkey)
            if key is not None and key not in shards_by_key:
                shards_by_key[key] = shard.data
    with _registry_lock:
        entry = _registry.setdefault(hex_id, _ObjectEntry(owned=False))
        entry.leaves[leaf] = _LeafEntry(
            desc=desc, array=array, shards=shards_by_key,
            nbytes=int(desc["nbytes"]))
        for key in shards_by_key:
            _shard_index[shard_id(binary, leaf, key)] = (hex_id, leaf, key)
    _report_device_bytes()
    return len(shards_by_key)


def local_array(obj_hex: str, leaf: int):
    """Zero-copy hit: the original (or previously assembled) array, by
    reference. None when this process holds no copy."""
    with _registry_lock:
        entry = _registry.get(obj_hex)
        if entry is None or entry.donated:
            return None
        le = entry.leaves.get(leaf)
    if le is None or le.array is None:
        return None
    if getattr(le.array, "is_deleted", lambda: False)():
        return None
    return le.array


def holds(obj_hex: str) -> bool:
    with _registry_lock:
        entry = _registry.get(obj_hex)
        return entry is not None and not entry.donated


def drop(obj_hex: str, donated: bool = False) -> int:
    """Forget this process's copy (free / borrower release / donation).
    Returns the device bytes released."""
    with _registry_lock:
        entry = _registry.pop(obj_hex, None)
        if entry is None:
            return 0
        stale = [sid for sid, loc in _shard_index.items()
                 if loc[0] == obj_hex]
        for sid in stale:
            del _shard_index[sid]
    released = 0
    for le in entry.leaves.values():
        released += le.nbytes
        if donated and le.array is not None:
            try:
                le.array.delete()
            except Exception:  # lint: allow-silent(buffer already freed by jax)
                pass
        le.array = None
        le.shards.clear()
    _report_device_bytes()
    return released


def device_bytes() -> int:
    with _registry_lock:
        return sum(le.nbytes for entry in _registry.values()
                   for le in entry.leaves.values())


def _report_device_bytes() -> None:
    from ray_tpu.util import telemetry

    telemetry.set_gauge("ray_tpu_object_device_bytes", device_bytes(),
                        {"proc": telemetry.proc_tag()})


# ---------------------------------------------------------------------------
# serving shards (holder side)
# ---------------------------------------------------------------------------


def shard_view(shard_id_bytes: bytes):
    """Host view of one registered shard's bytes, or None. On CPU
    backends this is a zero-copy view of the device buffer; on real
    accelerators it stages exactly one shard to host."""
    with _registry_lock:
        loc = _shard_index.get(bytes(shard_id_bytes))
        if loc is None:
            return None
        entry = _registry.get(loc[0])
        if entry is None:
            return None
        le = entry.leaves.get(loc[1])
        if le is None:
            return None
        data = le.shards.get(loc[2])
    if data is None:
        return None
    return _host_view(data)


def _host_view(shard_data):
    """memoryview('B') over a shard's host bytes."""
    import numpy as np

    arr = np.asarray(shard_data)
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    # Custom dtypes (bfloat16 & friends) don't export a buffer format;
    # a uint8 view always does.
    return memoryview(arr.view(np.uint8).reshape(-1))


# ---------------------------------------------------------------------------
# assembly (consumer side of get)
# ---------------------------------------------------------------------------


def collect_leaf_refs(value: Any) -> List[DeviceLeafRef]:
    from ray_tpu.core import serialization

    found: List[DeviceLeafRef] = []

    def leaf_fn(x):
        if isinstance(x, DeviceLeafRef):
            found.append(x)
            return x
        return serialization.UNCHANGED

    serialization.map_tree(value, leaf_fn)
    return found


def substitute(value: Any, resolved: Dict[Tuple[str, int], Any]) -> Any:
    from ray_tpu.core import serialization

    def leaf_fn(x):
        if isinstance(x, DeviceLeafRef):
            return resolved[(x.obj_hex, x.leaf)]
        return serialization.UNCHANGED

    return serialization.map_tree(value, leaf_fn)


def _shard_np(desc: dict, key: int, buf):
    import numpy as np

    meta = next(s for s in desc["shards"] if s["key"] == key)
    arr = np.frombuffer(buf, dtype=np.uint8)[:meta["nbytes"]]
    return arr.view(_np_dtype(desc["dtype"])).reshape(
        tuple(meta["shape"]))


class LeafAssembler:
    """Incremental consumer-side assembly: each pulled shard lands on
    its device (``jax.device_put``) the moment it arrives, and its host
    staging buffer is released before the next shard needs one — peak
    host memory is pull-concurrency × shard size, never the array.

    ``land()`` runs on executor threads (possibly several at once);
    ``finalize()`` stitches the landed single-device arrays into the
    recorded sharding."""

    def __init__(self, desc: dict):
        self.desc = desc
        self._lock = _make_lock("device_objects.LeafAssembler._lock")
        self._arrays: List[Tuple[Any, Any]] = []  # (device, shard arr)
        self._partial = None
        self.fallback = False
        try:
            self.sharding, self._device_keys = build_sharding(desc)
            self._devices_by_key: Dict[int, list] = {}
            for device, key in self._device_keys.items():
                self._devices_by_key.setdefault(key, []).append(device)
        except Exception:
            # Local topology can't host the mesh: stitch on the default
            # device one shard at a time. Still no whole-array HOST
            # buffer — the partial lives on device.
            self.fallback = True

    @staticmethod
    def _land_piece(shard_np, device=None):
        """device_put one shard; returns (piece, absorbed_staging).

        XLA:CPU's device_put takes the ZERO-COPY path for aligned host
        arrays — the returned jax.Array then WRAPS the staging memory.
        That is the ideal landing (zero copies), but the staging buffer
        must not go back to the pool while the array lives: the caller
        forfeits it to the array when ``absorbed`` is True.
        block_until_ready covers async dispatch (on accelerators the
        host→HBM DMA may still be reading the staging buffer when
        device_put returns)."""
        import numpy as np

        import jax

        piece = jax.device_put(shard_np, device)
        jax.block_until_ready(piece)
        absorbed = False
        if jax.default_backend() == "cpu":
            try:
                absorbed = np.shares_memory(np.asarray(piece), shard_np)
            except Exception:
                absorbed = True  # can't prove otherwise: keep it safe
        return piece, absorbed

    def land(self, key: int, buf) -> bool:
        """Land one pulled shard on its device(s). Returns True when
        the staging buffer was absorbed as the device storage (caller
        must forfeit it instead of pooling it)."""
        import jax

        shard_np = _shard_np(self.desc, key, buf)
        if self.fallback:
            import jax.numpy as jnp

            meta = next(s for s in self.desc["shards"]
                        if s["key"] == key)
            piece, _absorbed = self._land_piece(shard_np)
            with self._lock:
                if self._partial is None:
                    self._partial = jnp.zeros(
                        tuple(self.desc["global_shape"]),
                        _np_dtype(self.desc["dtype"]))
                idx = tuple(slice(lo, hi) for lo, hi in meta["index"])
                self._partial = self._partial.at[idx].set(piece)
                # The stitch READS piece; only after it completes may
                # the staging buffer be reused (piece dies with this
                # frame, releasing any absorbed buffer).
                jax.block_until_ready(self._partial)
            return False
        absorbed = False
        landed = []
        for d in self._devices_by_key.get(key, []):
            piece, piece_absorbed = self._land_piece(shard_np, d)
            absorbed = absorbed or piece_absorbed
            landed.append((d, piece))
        with self._lock:
            self._arrays.extend(landed)
        return absorbed

    def finalize(self):
        import jax

        if self.fallback:
            return self._partial
        if self.desc["kind"] == KIND_SINGLE:
            return self._arrays[0][1]
        return jax.make_array_from_single_device_arrays(
            tuple(self.desc["global_shape"]), self.sharding,
            [a for _, a in self._arrays])


def assemble_leaf(desc: dict, shard_bytes: Dict[int, Any]):
    """Rebuild one leaf from fully-staged shard bytes (unit tests and
    same-host fast paths; the streaming consumer uses LeafAssembler)."""
    assembler = LeafAssembler(desc)
    for key, buf in shard_bytes.items():
        assembler.land(key, buf)
    return assembler.finalize()


def sharding_matches(array, desc: dict) -> bool:
    """Does a live array's sharding match its descriptor? (test helper
    and publish-time sanity check)"""
    try:
        fresh = _describe(array)
    except Exception:
        return False
    if fresh is None:
        return False
    return (fresh["kind"] == desc["kind"]
            and fresh["global_shape"] == desc["global_shape"]
            and fresh["dtype"] == desc["dtype"]
            and fresh.get("spec") == desc.get("spec")
            and fresh.get("mesh_axes") == desc.get("mesh_axes")
            and [s["index"] for s in fresh["shards"]]
            == [s["index"] for s in desc["shards"]])


# ---------------------------------------------------------------------------
# staging buffers (bounded host memory during pulls)
# ---------------------------------------------------------------------------


#: Released staging buffers are pooled (per exact size) up to this many
#: bytes: on lazy-memory microVM hosts a FRESH buffer page-faults at
#: ~25µs/page (the 0.18 GiB/s first-touch floor in BENCH_TRANSFER_r05),
#: so steady-state pulls must land in already-faulted pages.
STAGING_POOL_CAP = 768 << 20

_pool_lock = threading.Lock()
_pool: Dict[int, List[Any]] = {}
_pool_bytes = 0


class StagingBuffer:
    """One shard's host landing area; accounts the staging high-water
    mark so 'no whole-array host buffer' is a checkable property.
    Backed by a bounded free-list so steady-state pulls recycle
    already-faulted pages instead of paying the page-supply floor."""

    def __init__(self, nbytes: int):
        global _pool_bytes
        self.nbytes = nbytes
        self.array = None
        with _pool_lock:
            free = _pool.get(nbytes)
            if free:
                self.array = free.pop()
                _pool_bytes -= nbytes
        if self.array is None:
            import numpy as np

            self.array = np.empty(nbytes, dtype=np.uint8)
        _note_staging(nbytes)

    def view(self) -> memoryview:
        return memoryview(self.array)

    def release(self) -> None:
        global _pool_bytes
        _note_staging(-self.nbytes)
        arr, self.array = self.array, None
        if arr is None:
            return
        with _pool_lock:
            if _pool_bytes + self.nbytes <= STAGING_POOL_CAP:
                _pool.setdefault(self.nbytes, []).append(arr)
                _pool_bytes += self.nbytes

    def forfeit(self) -> None:
        """The buffer was absorbed as a device array's storage
        (XLA:CPU zero-copy device_put): stop accounting it as staging
        and NEVER pool it — the array owns it now."""
        _note_staging(-self.nbytes)
        self.array = None
