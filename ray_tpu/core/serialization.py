"""Object serialization.

Mirrors the reference's two-part envelope (reference:
python/ray/_private/serialization.py:110 SerializationContext — msgpack
metadata + pickle5 with out-of-band buffers at :415,433): a value is
serialized to a small inband pickle stream plus a list of out-of-band
buffers (numpy / jax host arrays contribute their backing memory directly,
zero-copy).  The buffers can be placed in shared memory and mapped back
without a copy on the consumer side.

Error objects are tagged in metadata so that ``get`` re-raises them.
"""

from __future__ import annotations

import dataclasses
import pickle
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import cloudpickle

from ray_tpu import exceptions as exc

# Metadata tags.
NORMAL = b"N"
ERROR = b"E"
ACTOR_HANDLE = b"A"
# The inband stream contains DeviceLeafRef placeholders for jax.Array
# leaves whose shards live in the device plane (core/device_objects.py);
# get() resolves them (zero-copy locally, per-shard pulls remotely).
DEVICE = b"D"

#: map_tree leaf-callback sentinel: "not a leaf I handle, recurse".
UNCHANGED = object()


@dataclass
class SerializedObject:
    metadata: bytes  # 1-byte tag
    inband: bytes  # pickle stream (references out-of-band buffers)
    buffers: List[Any] = field(default_factory=list)  # buffer-protocol objects

    def total_size(self) -> int:
        return len(self.inband) + sum(
            memoryview(b).nbytes for b in self.buffers
        )


def _to_host(value):
    """Convert jax.Array leaves to numpy so their memory is host-addressable.

    jax.Array does not expose the buffer protocol; device arrays must round
    trip through host memory to enter the object store (the ICI path for
    device-to-device transfer lives in the collective layer, not here).
    """
    try:
        import jax
    except ImportError:
        return value
    if isinstance(value, jax.Array):
        import numpy as np

        return np.asarray(value)
    return value


class _OutOfBandPickler(cloudpickle.CloudPickler):
    """Cloudpickle with protocol-5 buffer_callback and jax.Array reduction."""


def serialize(value: Any,
              device_exporter: Optional[Callable] = None
              ) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer) -> bool:
        buffers.append(buf)
        return False  # out-of-band

    metadata = NORMAL
    if device_exporter is not None:
        # Device plane first: qualifying jax.Array leaves become
        # DeviceLeafRef placeholders (their shards stay on device);
        # whatever it declines falls through to the host mapping below.
        value, exported = device_exporter(value)
        if exported:
            metadata = DEVICE
    value = _map_jax_arrays(value)
    # The C pickler is ~7x cheaper than cloudpickle for plain data (the
    # overwhelmingly common case for args/returns); cloudpickle is only
    # needed for closures/lambdas/locally-defined classes, which plain
    # pickle refuses — so try fast, fall back (reference: msgpack
    # envelope + pickle5, cloudpickle only for functions,
    # _private/serialization.py).
    try:
        inband = pickle.dumps(value, protocol=5,
                              buffer_callback=buffer_callback)
        if b"__main__" in inband:
            # The C pickler serialized a __main__-defined class/function
            # BY REFERENCE — unpicklable in a worker whose __main__ is
            # worker_main. Cloudpickle serializes those by value. (A
            # literal "__main__" string in user data merely costs the
            # slower path.)
            raise pickle.PicklingError("__main__ reference")
    except (pickle.PicklingError, TypeError, AttributeError):
        del buffers[:]
        inband = cloudpickle.dumps(value, protocol=5,
                                   buffer_callback=buffer_callback)
    return SerializedObject(
        metadata=metadata,
        inband=inband,
        buffers=[b.raw() for b in buffers],
    )


def map_tree(value: Any, leaf_fn: Callable[[Any], Any]) -> Any:
    """Structure-preserving map over the common container types.

    ``leaf_fn(x)`` returns a replacement, or the ``UNCHANGED`` sentinel
    to recurse into ``x``. Namedtuples and dataclasses keep their
    container TYPE (a plain ``tuple(...)`` rebuild would silently
    collapse a namedtuple — consumers indexing by field name would
    break); unchanged subtrees are returned identically (no pointless
    container churn). Unknown container types are left to pickle, which
    handles arbitrary nesting via __reduce__."""
    mapped = leaf_fn(value)
    if mapped is not UNCHANGED:
        return mapped
    if isinstance(value, tuple):
        parts = [map_tree(v, leaf_fn) for v in value]
        if all(a is b for a, b in zip(parts, value)):
            return value
        if hasattr(value, "_fields"):  # namedtuple: preserve the type
            return type(value)(*parts)
        return tuple(parts)
    if isinstance(value, list):
        parts = [map_tree(v, leaf_fn) for v in value]
        if all(a is b for a, b in zip(parts, value)):
            return value
        return parts
    if isinstance(value, dict):
        parts = {k: map_tree(v, leaf_fn) for k, v in value.items()}
        if all(parts[k] is value[k] for k in value):
            return value
        return parts
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        changes = {}
        for f in dataclasses.fields(value):
            try:
                old = getattr(value, f.name)
            except AttributeError:
                continue
            new = map_tree(old, leaf_fn)
            if new is not old:
                changes[f.name] = new
        if not changes:
            return value
        try:
            return dataclasses.replace(value, **changes)
        except (TypeError, ValueError):
            return value  # init=False / custom __init__: leave to pickle
    return value


def _map_jax_arrays(value):
    """Convert jax arrays (incl. inside tuples/lists/dicts/namedtuples/
    dataclasses) to numpy, preserving container types.

    Deep/unknown structures are handled by pickle itself calling
    __reduce__ on jax.Array, which jax supports (it pickles via numpy);
    this fast path avoids an extra copy for the common flat cases.
    """
    try:
        import jax
    except ImportError:
        return value

    def leaf_fn(x):
        if isinstance(x, jax.Array):
            return _to_host(x)
        return UNCHANGED

    return map_tree(value, leaf_fn)


def serialize_error(err: BaseException, task_name: str = "") -> SerializedObject:
    if isinstance(err, exc.RayTpuError):
        # System errors (ActorDiedError, WorkerCrashedError, cancellation,
        # or an already-wrapped TaskError from a nested failure) surface
        # as-is at get().
        task_error = err
    else:
        task_error = exc.TaskError(
            cause_cls_name=type(err).__name__,
            cause_repr=repr(err),
            traceback_str="".join(
                traceback.format_exception(type(err), err, err.__traceback__)
            ),
            task_name=task_name,
        )
    try:
        inband = cloudpickle.dumps(task_error, protocol=5)
    except Exception:
        # The original exception may not be picklable; fall back to the
        # string form.
        inband = cloudpickle.dumps(
            exc.TaskError(
                cause_cls_name=type(err).__name__,
                cause_repr=repr(err),
                traceback_str=task_error.traceback_str,
                task_name=task_name,
            ),
            protocol=5,
        )
    return SerializedObject(metadata=ERROR, inband=inband, buffers=[])


def deserialize(metadata: bytes, inband: bytes, buffers: Sequence[Any]) -> Any:
    value = pickle.loads(inband, buffers=[pickle.PickleBuffer(b) for b in buffers])
    if metadata == ERROR:
        raise value
    return value


def deserialize_no_raise(metadata: bytes, inband: bytes, buffers: Sequence[Any]):
    """Returns (value, is_error) without raising."""
    value = pickle.loads(inband, buffers=[pickle.PickleBuffer(b) for b in buffers])
    return value, metadata == ERROR


def dumps_control(obj: Any) -> bytes:
    """Serialize control-plane payloads (task specs, descriptors).

    TaskSpec — the per-task hot path — uses a hand-rolled msgpack codec
    (~10x cheaper than cloudpickle; the reference ships specs as
    protobuf, common.proto TaskSpec, for the same reason). Everything
    else falls back to cloudpickle. A one-byte tag disambiguates.
    """
    from ray_tpu.core.task_spec import TaskSpec

    if type(obj) is TaskSpec:
        fast = _dump_spec_fast(obj)
        if fast is not None:
            return fast
    return _CTRL_PICKLE + cloudpickle.dumps(obj, protocol=5)


def spec_task_id_from_blob(data: bytes) -> Optional[str]:
    """Best-effort task-id (hex) extraction from a control blob whose
    full decode failed — lets the worker still send a task_done error
    for a spec it cannot run (protocol-bug path, worker_main
    h_push_tasks)."""
    if data[:1] != _CTRL_SPEC:
        return None
    try:
        import msgpack

        row = msgpack.unpackb(data[1:], raw=False, use_list=True)
        tid = row[0]
        return tid.hex() if isinstance(tid, bytes) else None
    except Exception:  # noqa: BLE001
        return None


def loads_control(data: bytes) -> Any:
    tag = data[:1]
    if tag == _CTRL_SPEC:
        return _load_spec_fast(data)
    if tag == _CTRL_PICKLE:
        return pickle.loads(data[1:])
    return pickle.loads(data)  # legacy untagged stream


# -- fast TaskSpec codec -----------------------------------------------------

_CTRL_PICKLE = b"\x00"
_CTRL_SPEC = b"\x01"


def _pack_address(a) -> Any:
    return None if a is None else [a.host, a.port, a.worker_id_hex]


def _pack_arg(arg) -> list:
    inline = None
    if arg.inline is not None:
        metadata, inband, buffers = arg.inline
        inline = [bytes(metadata), bytes(inband),
                  [bytes(memoryview(b)) for b in buffers]]
    return [
        inline,
        arg.object_id.binary() if arg.object_id is not None else None,
        _pack_address(arg.owner),
    ]


def _pack_strategy(s) -> Any:
    from ray_tpu.core import task_spec as ts

    if type(s) is ts.DefaultSchedulingStrategy:
        return 0
    if type(s) is ts.SpreadSchedulingStrategy:
        return 1
    if type(s) is ts.NodeAffinitySchedulingStrategy:
        return [2, s.node_id_hex, s.soft]
    if type(s) is ts.PlacementGroupSchedulingStrategy:
        return [3, s.placement_group_id_hex, s.bundle_index,
                s.capture_child_tasks]
    return None  # unknown subclass: caller falls back to cloudpickle


def _dump_spec_fast(spec) -> bytes:
    import msgpack

    strategy = _pack_strategy(spec.scheduling_strategy)
    if strategy is None:
        return None
    runtime_env = spec.runtime_env
    try:
        row = [
            spec.task_id.binary(),
            spec.job_id.binary(),
            spec.task_type.value,
            spec.name,
            spec.function_key,
            [_pack_arg(a) for a in spec.args],
            spec.num_returns,
            dict(spec.resources),
            _pack_address(spec.owner),
            spec.max_retries,
            spec.retry_exceptions,
            strategy,
            runtime_env,
            spec.actor_id.binary() if spec.actor_id is not None else None,
            spec.method_name,
            spec.seqno,
            spec.concurrency_group,
            spec.max_restarts,
            spec.max_task_retries,
            spec.max_concurrency,
            spec.is_async_actor,
            spec.actor_name,
            spec.namespace,
            bool(getattr(spec, "detached", False)),
            spec.stream_window,
        ]
        return _CTRL_SPEC + msgpack.packb(row, use_bin_type=True)
    except (TypeError, ValueError):
        # Non-msgpack-able payload somewhere (e.g. exotic runtime_env
        # value): let cloudpickle handle it.
        return None


def _unpack_address(a):
    from ray_tpu.core.task_spec import Address

    return None if a is None else Address(a[0], a[1], a[2])


def _load_spec_fast(data: bytes):
    import msgpack

    from ray_tpu.core import task_spec as ts
    from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID

    row = msgpack.unpackb(data[1:], raw=False)
    strategy_row = row[11]
    if strategy_row == 0:
        strategy = ts.DefaultSchedulingStrategy()
    elif strategy_row == 1:
        strategy = ts.SpreadSchedulingStrategy()
    elif strategy_row[0] == 2:
        strategy = ts.NodeAffinitySchedulingStrategy(
            node_id_hex=strategy_row[1], soft=strategy_row[2])
    else:
        strategy = ts.PlacementGroupSchedulingStrategy(
            placement_group_id_hex=strategy_row[1],
            bundle_index=strategy_row[2],
            capture_child_tasks=strategy_row[3])
    args = [
        ts.TaskArg(
            inline=(a[0][0], a[0][1], a[0][2]) if a[0] is not None else None,
            object_id=ObjectID(a[1]) if a[1] is not None else None,
            owner=_unpack_address(a[2]),
        )
        for a in row[5]
    ]
    spec = ts.TaskSpec(
        task_id=TaskID(row[0]),
        job_id=JobID(row[1]),
        task_type=ts.TaskType(row[2]),
        name=row[3],
        function_key=row[4],
        args=args,
        num_returns=row[6],
        resources=row[7],
        owner=_unpack_address(row[8]),
        max_retries=row[9],
        retry_exceptions=row[10],
        scheduling_strategy=strategy,
        runtime_env=row[12],
        actor_id=ActorID(row[13]) if row[13] is not None else None,
        method_name=row[14],
        seqno=row[15],
        concurrency_group=row[16],
        max_restarts=row[17],
        max_task_retries=row[18],
        max_concurrency=row[19],
        is_async_actor=row[20],
        actor_name=row[21],
        namespace=row[22],
        stream_window=row[24] if len(row) > 24 else 0,
    )
    spec.detached = row[23]
    return spec
