"""Object serialization.

Mirrors the reference's two-part envelope (reference:
python/ray/_private/serialization.py:110 SerializationContext — msgpack
metadata + pickle5 with out-of-band buffers at :415,433): a value is
serialized to a small inband pickle stream plus a list of out-of-band
buffers (numpy / jax host arrays contribute their backing memory directly,
zero-copy).  The buffers can be placed in shared memory and mapped back
without a copy on the consumer side.

Error objects are tagged in metadata so that ``get`` re-raises them.
"""

from __future__ import annotations

import pickle
import traceback
from dataclasses import dataclass, field
from typing import Any, List, Sequence

import cloudpickle

from ray_tpu import exceptions as exc

# Metadata tags.
NORMAL = b"N"
ERROR = b"E"
ACTOR_HANDLE = b"A"


@dataclass
class SerializedObject:
    metadata: bytes  # 1-byte tag
    inband: bytes  # pickle stream (references out-of-band buffers)
    buffers: List[Any] = field(default_factory=list)  # buffer-protocol objects

    def total_size(self) -> int:
        return len(self.inband) + sum(
            memoryview(b).nbytes for b in self.buffers
        )


def _to_host(value):
    """Convert jax.Array leaves to numpy so their memory is host-addressable.

    jax.Array does not expose the buffer protocol; device arrays must round
    trip through host memory to enter the object store (the ICI path for
    device-to-device transfer lives in the collective layer, not here).
    """
    try:
        import jax
    except ImportError:
        return value
    if isinstance(value, jax.Array):
        import numpy as np

        return np.asarray(value)
    return value


class _OutOfBandPickler(cloudpickle.CloudPickler):
    """Cloudpickle with protocol-5 buffer_callback and jax.Array reduction."""


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer) -> bool:
        buffers.append(buf)
        return False  # out-of-band

    value = _map_jax_arrays(value)
    inband = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
    return SerializedObject(
        metadata=NORMAL,
        inband=inband,
        buffers=[b.raw() for b in buffers],
    )


def _map_jax_arrays(value):
    """Shallowly convert jax arrays (incl. inside tuples/lists/dicts) to numpy.

    Deep structures are handled by pickle itself calling __reduce__ on
    jax.Array, which jax supports (it pickles via numpy); this fast path
    avoids an extra copy for the common flat cases.
    """
    try:
        import jax
    except ImportError:
        return value
    if isinstance(value, jax.Array):
        return _to_host(value)
    if isinstance(value, tuple):
        return tuple(_map_jax_arrays(v) for v in value)
    if isinstance(value, list):
        return [_map_jax_arrays(v) for v in value]
    if isinstance(value, dict):
        return {k: _map_jax_arrays(v) for k, v in value.items()}
    return value


def serialize_error(err: BaseException, task_name: str = "") -> SerializedObject:
    if isinstance(err, exc.RayTpuError):
        # System errors (ActorDiedError, WorkerCrashedError, cancellation,
        # or an already-wrapped TaskError from a nested failure) surface
        # as-is at get().
        task_error = err
    else:
        task_error = exc.TaskError(
            cause_cls_name=type(err).__name__,
            cause_repr=repr(err),
            traceback_str="".join(
                traceback.format_exception(type(err), err, err.__traceback__)
            ),
            task_name=task_name,
        )
    try:
        inband = cloudpickle.dumps(task_error, protocol=5)
    except Exception:
        # The original exception may not be picklable; fall back to the
        # string form.
        inband = cloudpickle.dumps(
            exc.TaskError(
                cause_cls_name=type(err).__name__,
                cause_repr=repr(err),
                traceback_str=task_error.traceback_str,
                task_name=task_name,
            ),
            protocol=5,
        )
    return SerializedObject(metadata=ERROR, inband=inband, buffers=[])


def deserialize(metadata: bytes, inband: bytes, buffers: Sequence[Any]) -> Any:
    value = pickle.loads(inband, buffers=[pickle.PickleBuffer(b) for b in buffers])
    if metadata == ERROR:
        raise value
    return value


def deserialize_no_raise(metadata: bytes, inband: bytes, buffers: Sequence[Any]):
    """Returns (value, is_error) without raising."""
    value = pickle.loads(inband, buffers=[pickle.PickleBuffer(b) for b in buffers])
    return value, metadata == ERROR


def dumps_control(obj: Any) -> bytes:
    """Serialize control-plane payloads (task specs, descriptors)."""
    return cloudpickle.dumps(obj, protocol=5)


def loads_control(data: bytes) -> Any:
    return pickle.loads(data)
