"""Lightweight RPC transport.

Equivalent of the reference's gRPC layer (reference: src/ray/rpc/
grpc_server.h:85 and client wrappers): length-prefixed msgpack frames over
TCP asyncio streams. Connections are **symmetric** — after the handshake
either peer can issue requests — which subsumes both the request/reply RPCs
and the long-poll pubsub pushes of the reference
(reference: src/ray/pubsub/publisher.h:307) with a single mechanism.

Every process runs one event loop in a dedicated daemon thread
(``EventLoopThread``); synchronous callers bridge with
``run_coroutine_threadsafe``.

Wire format: 4-byte little-endian length, then msgpack map:
  {"t": "req"|"res"|"ntf", "i": request_id, "m": method,
   "d": payload (msgpack-native; complex values pre-pickled by callers),
   "e": error string or None}
"""

from __future__ import annotations

import asyncio
import contextlib
import itertools
import logging
import os
import threading
import time
from fnmatch import fnmatchcase
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import msgpack

logger = logging.getLogger(__name__)

MAX_FRAME = 1 << 31

Handler = Callable[["Connection", Any], Awaitable[Any]]

# Telemetry is bound lazily: this module is imported during core
# bootstrap, before ray_tpu.util (whose package init pulls in higher
# layers) is safe to import. First use is always post-bootstrap.
_telemetry = None


def _tm():
    global _telemetry
    if _telemetry is None:
        from ray_tpu.util import telemetry

        _telemetry = telemetry
    return _telemetry


# Flight recorder, bound lazily for the same bootstrap-order reason.
_flight = None


def _fr():
    global _flight
    if _flight is None:
        from ray_tpu.util import flight_recorder

        _flight = flight_recorder
    return _flight


# Server-side accounting (util/rpc_stats.py), bound lazily likewise.
_rpc_stats = None


def _rs():
    global _rpc_stats
    if _rpc_stats is None:
        from ray_tpu.util import rpc_stats

        _rpc_stats = rpc_stats
    return _rpc_stats


#: Cached config gate for per-RPC client/server spans (``trace_rpc`` /
#: RAY_TPU_TRACE_RPC). None until first read; tests reset it directly.
_trace_rpc_flag: Optional[bool] = None


def _rpc_tracing_on() -> bool:
    global _trace_rpc_flag
    if _trace_rpc_flag is None:
        try:
            from ray_tpu.core.config import get_config

            _trace_rpc_flag = bool(get_config().trace_rpc)
        except Exception:
            _trace_rpc_flag = os.environ.get(
                "RAY_TPU_TRACE_RPC", "").lower() in ("1", "true", "yes")
    if not _trace_rpc_flag:
        return False
    from ray_tpu.util import tracing

    tracing.maybe_setup_worker_tracing()
    return tracing.is_enabled()


#: Requests awaiting replies in this process. Locked: a process can run
#: several event loops (driver + embedded head), and an unsynchronized
#: read-modify-write would let the gauge drift permanently.
_in_flight = 0
_in_flight_lock = threading.Lock()


def _track_in_flight(delta: int) -> None:
    global _in_flight
    with _in_flight_lock:
        _in_flight += delta
        count = _in_flight
    tm = _tm()
    tm.set_gauge("ray_tpu_rpc_in_flight_requests", count,
                 {"proc": tm.proc_tag()})


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    """Peer connection died. ``sent=False`` means the request was never
    written to the socket (connection already closed), so the callee
    definitely never saw it — callers may retry without side-effect or
    at-most-once concerns."""

    def __init__(self, msg: str = "", sent: bool = True):
        super().__init__(msg)
        self.sent = sent


# ---------------------------------------------------------------------------
# Network fault-injection plane (reference: Ray's chaos suites inject
# network faults below the RPC clients — test_utils' kill-based killers
# plus gRPC-level fault hooks). Rules live in a per-process injector;
# every frame consults it ONLY when rules are installed, so the hot send
# path pays a single module-global None check when the plane is idle.
# ---------------------------------------------------------------------------

DROP = "drop"
DELAY = "delay"
DUPLICATE = "duplicate"
PARTITION = "partition"
FAULT_ACTIONS = (DROP, DELAY, DUPLICATE, PARTITION)


class FaultRule:
    """One matchable fault. Matching is by direction ('send'/'recv'),
    peer (fnmatch on Connection.name) and RPC method (fnmatch; response
    frames carry no method and only match a '*' method pattern)."""

    __slots__ = ("action", "peer", "method", "direction", "probability",
                 "delay_s", "jitter_s", "max_matches", "duration_s",
                 "rule_id", "matches", "installed_at")

    def __init__(self, action: str, peer: str = "*", method: str = "*",
                 direction: str = "both", probability: float = 1.0,
                 delay_s: float = 0.0, jitter_s: float = 0.0,
                 max_matches: int = 0, duration_s: float = 0.0):
        if action not in FAULT_ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        if direction not in ("send", "recv", "both"):
            raise ValueError(f"unknown fault direction {direction!r}")
        self.action = action
        self.peer = peer
        self.method = method
        self.direction = direction
        self.probability = probability
        self.delay_s = delay_s
        self.jitter_s = jitter_s
        self.max_matches = max_matches
        self.duration_s = duration_s
        self.rule_id = 0
        self.matches = 0
        self.installed_at = 0.0

    def expired(self, now: float) -> bool:
        if self.duration_s and now - self.installed_at >= self.duration_s:
            return True
        return bool(self.max_matches and self.matches >= self.max_matches)

    def __repr__(self):
        return (f"FaultRule(#{self.rule_id} {self.action} peer={self.peer!r} "
                f"method={self.method!r} dir={self.direction} "
                f"p={self.probability} matches={self.matches})")


class FaultInjector:
    """Deterministic (seeded) per-process fault plane.

    Tests install rules at runtime to script partitions around specific
    calls; deployments can pre-install rules via RAY_TPU_FAULT_INJECTION_*
    env vars (see core/config.py). All decisions flow through one seeded
    RNG, so a fixed seed reproduces the exact same drop/delay pattern.
    """

    def __init__(self, seed: int = 0):
        import random

        self.rng = random.Random(seed)
        self.rules: List[FaultRule] = []
        self.stats: Dict[str, int] = {a: 0 for a in FAULT_ACTIONS}
        self._lock = threading.Lock()
        self._next_id = itertools.count(1)

    def install(self, action: str, **kwargs) -> int:
        """Install a rule; returns its id for targeted clear()."""
        rule = action if isinstance(action, FaultRule) \
            else FaultRule(action, **kwargs)
        with self._lock:
            rule.rule_id = next(self._next_id)
            rule.installed_at = time.monotonic()
            self.rules.append(rule)
        logger.info("fault rule installed: %r", rule)
        return rule.rule_id

    def clear(self, rule_id: Optional[int] = None) -> None:
        """Remove one rule by id, or every rule when id is None."""
        with self._lock:
            if rule_id is None:
                self.rules.clear()
            else:
                self.rules = [r for r in self.rules
                              if r.rule_id != rule_id]

    def reset(self) -> None:
        with self._lock:
            self.rules.clear()
            self.stats = {a: 0 for a in FAULT_ACTIONS}

    def on_frame(self, direction: str, peer: str, method: Optional[str]
                 ) -> Optional[Tuple[str, float]]:
        """First-matching-rule verdict for one frame, or None to pass
        through. Returns (action, delay_s)."""
        now = time.monotonic()
        with self._lock:
            live = [r for r in self.rules if not r.expired(now)]
            if len(live) != len(self.rules):
                self.rules = live
            for rule in live:
                if rule.direction != "both" and rule.direction != direction:
                    continue
                if not fnmatchcase(peer or "", rule.peer):
                    continue
                if method is None:
                    # Response frames carry no method: only a wildcard
                    # method pattern (blanket rules, partitions) matches.
                    if rule.method != "*":
                        continue
                elif not fnmatchcase(method, rule.method):
                    continue
                if rule.probability < 1.0 and \
                        self.rng.random() >= rule.probability:
                    continue
                rule.matches += 1
                self.stats[rule.action] = self.stats.get(rule.action, 0) + 1
                _tm().inc("ray_tpu_rpc_faults_injected_total", 1,
                          {"action": rule.action})
                _fr().record("rpc", "fault_injected", severity="warn",
                             action=rule.action, direction=direction,
                             peer=peer or "", method=method or "")
                delay = rule.delay_s
                if rule.jitter_s:
                    delay += self.rng.random() * rule.jitter_s
                return rule.action, delay
        return None


#: None until someone enables injection — the idle-plane hot-path check
#: is a single global load + None test.
_fault_injector: Optional[FaultInjector] = None
_env_checked = False


def get_fault_injector() -> FaultInjector:
    """The process's injector, created on first use. Seeded through
    core/config.py (``fault_injection_seed`` — env var or
    ``system_config``), falling back to the raw env var during partial
    bootstrap."""
    global _fault_injector
    if _fault_injector is None:
        try:
            from ray_tpu.core.config import get_config

            seed = get_config().fault_injection_seed
        except Exception:
            seed = int(os.environ.get("RAY_TPU_FAULT_INJECTION_SEED",
                                      "0"))
        _fault_injector = FaultInjector(seed=seed)
    return _fault_injector


def reset_fault_injector() -> None:
    """Drop the process injector entirely (tests restore the zero-cost
    disabled state)."""
    global _fault_injector
    _fault_injector = None


def _maybe_init_fault_injection_from_env() -> None:
    """Activate configured rules once per process (checked lazily on
    the first Connection, so worker processes spawned with the
    RAY_TPU_FAULT_INJECTION_* env vars inherit the plane without any
    init-order coupling). Reads through core/config.py so both env vars
    and ``system_config`` overrides apply."""
    global _env_checked
    if _env_checked:
        return
    _env_checked = True
    try:
        from ray_tpu.core.config import get_config

        cfg = get_config()
        enabled = cfg.fault_injection_enabled
        rules_json = cfg.fault_injection_rules
        seed = cfg.fault_injection_seed
    except Exception:  # config unavailable (partial bootstrap): raw env
        enabled = os.environ.get(
            "RAY_TPU_FAULT_INJECTION_ENABLED", "").lower() in (
                "1", "true", "yes")
        rules_json = os.environ.get("RAY_TPU_FAULT_INJECTION_RULES", "")
        seed = int(os.environ.get("RAY_TPU_FAULT_INJECTION_SEED", "0"))
    if not enabled and not rules_json:
        return
    global _fault_injector
    if _fault_injector is None:
        _fault_injector = FaultInjector(seed=seed)
    if rules_json:
        import json

        try:
            for spec in json.loads(rules_json):
                action = spec.pop("action")
                _fault_injector.install(action, **spec)
        except Exception:
            logger.exception("bad RAY_TPU_FAULT_INJECTION_RULES; ignored")


# StreamReader buffer: the data plane ships MiB chunk frames; the
# default 64KB limit turns each into ~16 small reads + wakeups.
READ_LIMIT = 8 << 20


class WithAttachment:
    """Handler return wrapper: ``payload`` rides the msgpack frame,
    ``attachment`` (bytes/memoryview) rides after it as a RAW sidecar —
    the data plane's bulk bytes skip the msgpack pack/unpack copies and
    the coalescing join (reference: the object manager's dedicated data
    plane vs the gRPC control plane). The receiver finds the bytes under
    ``payload["__attachment__"]``."""

    __slots__ = ("payload", "attachment")

    def __init__(self, payload, attachment):
        self.payload = payload
        self.attachment = attachment


class Connection:
    """One bidirectional peer connection."""

    # Above this many buffered bytes, senders await drain (backpressure).
    WRITE_HIGH_WATER = 4 << 20

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Dict[str, Handler], name: str = ""):
        _maybe_init_fault_injection_from_env()
        self.reader = reader
        self.writer = writer
        self.handlers = handlers
        self.name = name
        self._req_counter = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._read_task: Optional[asyncio.Task] = None
        self.on_close: Optional[Callable[["Connection"], None]] = None
        # Write coalescing: frames queued within one loop tick flush as a
        # single writer.write (one syscall for a burst of small RPCs).
        self._outbuf: list = []
        self._flush_scheduled = False
        # Cross-thread write fence: the executor's synchronous reply
        # fast path (try_notify_sync) and the loop's _flush must not
        # interleave bytes of different frames on the socket.
        self._write_mutex = threading.Lock()
        # Lazily dup'ed real socket for try_notify_sync (asyncio only
        # exposes a send-less TransportSocket wrapper).
        self._sock = None
        self._sock_tried = False
        # Arbitrary per-connection state (e.g. registered worker id,
        # caller kind stamped by the registration handlers).
        self.state: Dict[str, Any] = {}
        # Size of the most recent frame handed to _enqueue_now: read by
        # _dispatch right after sending a reply to attribute reply
        # bytes per handler (best-effort under concurrent sends).
        self._last_enqueue_nbytes = 0

    def start(self):
        self._loop = asyncio.get_running_loop()
        self._read_task = self._loop.create_task(self._read_loop())

    async def _read_loop(self):
        try:
            while True:
                head = await self.reader.readexactly(4)
                length = int.from_bytes(head, "little")
                if length > MAX_FRAME:
                    raise RpcError(f"frame too large: {length}")
                body = await self.reader.readexactly(length)
                nbytes = 4 + length
                msg = msgpack.unpackb(body, raw=False)
                if msg.pop("b", False):
                    # Raw sidecar attachment follows the frame.
                    blen = int.from_bytes(
                        await self.reader.readexactly(8), "little")
                    if blen > MAX_FRAME:
                        raise RpcError(
                            f"attachment too large: {blen}")
                    blob = await self.reader.readexactly(blen)
                    nbytes += 8 + blen
                    d = msg.get("d")
                    if not isinstance(d, dict):
                        d = {} if d is None else {"value": d}
                        msg["d"] = d
                    d["__attachment__"] = blob
                _tm().inc("ray_tpu_rpc_recv_bytes_total", nbytes)
                # Local-only accounting stamps (never re-serialized):
                # queue wait = this read timestamp to handler start.
                msg["_rts"] = time.perf_counter()
                msg["_rbs"] = nbytes
                fi = _fault_injector
                if fi is not None and fi.rules:
                    verdict = fi.on_frame("recv", self.name, msg.get("m"))
                    if verdict is not None:
                        action, delay = verdict
                        if action in (DROP, PARTITION):
                            continue  # inbound frame lost on the wire
                        if action == DELAY:
                            asyncio.get_running_loop().call_later(
                                delay, self._process_frame, msg)
                            continue
                        if action == DUPLICATE:
                            self._process_frame(msg)
                self._process_frame(msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("rpc read loop error on %s", self.name)
        finally:
            await self._teardown()

    def _process_frame(self, msg: dict) -> None:
        """Route one inbound frame (factored from the read loop so the
        fault plane can delay/duplicate processing)."""
        t = msg["t"]
        if t == "res":
            fut = self._pending.pop(msg["i"], None)
            if fut is not None and not fut.done():
                if msg.get("e"):
                    fut.set_exception(RpcError(msg["e"]))
                else:
                    fut.set_result(msg.get("d"))
        elif t == "ntf":
            handler = self.handlers.get(msg.get("m"))
            if handler is not None and not \
                    asyncio.iscoroutinefunction(handler):
                # Sync fast path: notification handlers that
                # never await run inline — one asyncio Task per
                # tiny-task completion is the dominant loop
                # overhead at high task rates.
                t0 = time.perf_counter()
                ok = True
                try:
                    handler(self, msg.get("d"))
                except Exception:
                    ok = False
                    logger.exception("notify handler %s failed",
                                     msg.get("m"))
                if _tm().enabled():
                    rts = msg.get("_rts")
                    rs = _rs()
                    rs.server_stats().record(
                        msg.get("m") or "?", rs.caller_kind(self),
                        max(0.0, t0 - rts) if rts is not None else 0.0,
                        time.perf_counter() - t0,
                        recv_bytes=msg.get("_rbs") or 0, ok=ok)
            else:
                self._loop.create_task(self._dispatch(t, msg))
        elif t == "req":
            self._loop.create_task(self._dispatch(t, msg))

    async def _dispatch(self, t: str, msg: dict):
        method = msg.get("m")
        handler = self.handlers.get(method)
        error = None
        result = None
        t0 = time.perf_counter()
        if handler is None:
            error = f"no handler for method {method!r}"
        else:
            with contextlib.ExitStack() as stack:
                tc = msg.get("tc")
                if tc is not None and _rpc_tracing_on():
                    from ray_tpu.util import tracing

                    stack.enter_context(
                        tracing.span(f"rpc.handle {method}", tc))
                try:
                    result = await handler(self, msg.get("d"))
                except Exception as e:
                    logger.exception("handler %s failed", method)
                    error = f"{type(e).__name__}: {e}"
        t1 = time.perf_counter()
        reply_bytes = 0
        if t == "req":
            attachment = None
            if isinstance(result, WithAttachment):
                attachment = result.attachment
                result = result.payload
            await self._send({"t": "res", "i": msg["i"], "d": result,
                              "e": error}, attachment)
            reply_bytes = self._last_enqueue_nbytes
        if _tm().enabled():
            rts = msg.get("_rts")
            rs = _rs()
            rs.server_stats().record(
                method or "?", rs.caller_kind(self),
                max(0.0, t0 - rts) if rts is not None else 0.0,
                t1 - t0, recv_bytes=msg.get("_rbs") or 0,
                reply_bytes=reply_bytes, ok=error is None)

    def _enqueue_frame(self, msg: dict, attachment=None) -> bool:
        """Fault-plane gate in front of ``_enqueue_now``: with no rules
        installed this is one module-global load + None check (the
        acceptance bar for the disabled plane's hot-path overhead)."""
        fi = _fault_injector
        if fi is not None and fi.rules:
            verdict = fi.on_frame("send", self.name, msg.get("m"))
            if verdict is not None:
                action, delay = verdict
                if action == DROP:
                    return False  # frame lost on the wire; caller unaware
                if action == PARTITION:
                    # A partitioned peer is unreachable: surface the same
                    # error an already-closed transport would, with
                    # sent=False (the frame provably never left).
                    raise ConnectionLost(
                        f"injected partition to {self.name}", sent=False)
                if action == DELAY:
                    def _later(msg=msg, attachment=attachment):
                        if self._closed:
                            return
                        try:
                            if self._enqueue_now(msg, attachment):
                                self._flush()
                        except Exception:
                            pass  # teardown race; read loop owns cleanup
                    asyncio.get_running_loop().call_later(delay, _later)
                    return False
                if action == DUPLICATE:
                    self._enqueue_now(msg, attachment)
        return self._enqueue_now(msg, attachment)

    def _enqueue_now(self, msg: dict, attachment=None) -> bool:
        """Append one frame (plus optional raw attachment) to the
        coalescing buffer and schedule the flush. Returns True when the
        transport is above the high-water mark (caller decides how to
        backpressure). No awaits — the frame append is atomic."""
        if self._closed:
            raise ConnectionLost(self.name, sent=False)
        if attachment is not None:
            msg["b"] = True
        data = msgpack.packb(msg, use_bin_type=True)
        nbytes = 4 + len(data)
        self._outbuf.append(len(data).to_bytes(4, "little"))
        self._outbuf.append(data)
        if attachment is not None:
            mv = memoryview(attachment).cast("B")
            nbytes += 8 + mv.nbytes
            self._outbuf.append(mv.nbytes.to_bytes(8, "little"))
            self._outbuf.append(mv)  # flushed without joining (below)
        _tm().inc("ray_tpu_rpc_sent_bytes_total", nbytes)
        self._last_enqueue_nbytes = nbytes
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._flush)
        transport = self.writer.transport
        return (transport is not None and
                transport.get_write_buffer_size() > self.WRITE_HIGH_WATER)

    async def _send(self, msg: dict, attachment=None):
        if self._enqueue_frame(msg, attachment):
            self._flush()
            await self.writer.drain()

    def _flush(self):
        self._flush_scheduled = False
        if self._closed or not self._outbuf:
            return
        pieces, self._outbuf = self._outbuf, []
        # Coalesce small control frames into one write, but hand bulk
        # attachment buffers to the transport directly — joining a MiB
        # chunk would re-copy the entire data plane.
        small: list = []
        with self._write_mutex:  # fence vs try_notify_sync mid-frame
            try:
                for piece in pieces:
                    if len(piece) >= (64 << 10):
                        if small:
                            self.writer.write(b"".join(small))
                            small = []
                        self.writer.write(piece)
                    else:
                        small.append(piece)
                if small:
                    self.writer.write(b"".join(small))
            except Exception:
                pass  # read loop notices the broken pipe and tears down

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None) -> Any:
        req_id = next(self._req_counter)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        msg = {"t": "req", "i": req_id, "m": method, "d": payload}
        # ExitStack so a failing call closes the client span with the
        # real exception info (error status on otel spans).
        with contextlib.ExitStack() as stack:
            if _rpc_tracing_on():
                from ray_tpu.util import tracing

                stack.enter_context(tracing.span(f"rpc {method}"))
                carrier = tracing.inject_context()
                if carrier:
                    # Carrier rides the frame; receivers without the
                    # flag ignore the extra key.
                    msg["tc"] = carrier
            t0 = time.perf_counter()
            _track_in_flight(1)
            try:
                try:
                    await self._send(msg)
                except Exception:
                    self._pending.pop(req_id, None)
                    raise
                if timeout is not None:
                    return await asyncio.wait_for(fut, timeout)
                return await fut
            finally:
                _track_in_flight(-1)
                _tm().observe("ray_tpu_rpc_client_latency_seconds",
                              time.perf_counter() - t0,
                              {"method": method})

    async def notify(self, method: str, payload: Any = None):
        await self._send({"t": "ntf", "i": 0, "m": method, "d": payload})

    def notify_forget(self, method: str, payload: Any = None) -> None:
        """Fire-and-forget notification, silencing transport errors —
        the peer that raced away cannot receive it, and an
        unretrieved-task traceback on every clean shutdown (pubsub to a
        just-closed subscriber, kill to a dying worker) is noise, not
        signal. Callers that need delivery feedback await notify().
        Loop-thread only (rides notify_nowait's enqueue + flush)."""
        try:
            self.notify_nowait(method, payload)
        except (RpcError, OSError, RuntimeError):
            pass

    def notify_nowait(self, method: str, payload: Any = None):
        """Fire-and-forget notification without coroutine machinery —
        the hot completion path sends one of these per finished task.
        Backpressure degrades to an eager flush instead of awaiting
        drain (small frames; the transport buffers)."""
        if self._enqueue_frame({"t": "ntf", "i": 0, "m": method,
                                "d": payload}):
            self._flush()

    def try_notify_sync(self, method: str, payload: Any = None) -> bool:
        """Synchronous fire-and-forget from a NON-loop thread — the
        task executor's reply fast path. On success the frame's bytes
        are in the kernel when this returns, which (a) satisfies the
        delivery barrier without an executor⇄loop ping-pong and (b) on
        a one-core host removes two context switches from every task
        reply. Returns False — caller falls back to the loop path —
        whenever frame ordering or atomicity can't be guaranteed: no
        raw socket, connection closed/closing, frames waiting in the
        coalescing buffer, bytes pending in the transport, or the loop
        currently mid-flush."""
        if self._closed:
            return False
        fi = _fault_injector
        if fi is not None and fi.rules:
            # Fault rules apply on the loop path only; bypassing them
            # through the raw socket would let frames dodge an installed
            # partition.
            return False
        sock = self._sock
        if sock is None:
            if self._sock_tried:
                return False
            self._sock_tried = True
            try:
                tr = self.writer.get_extra_info("socket")
                fd = tr.fileno() if tr is not None else -1
                if fd < 0:
                    return False
                import os as _os
                import socket as _socket

                # dup shares the file description (already O_NONBLOCK
                # via asyncio) but gives us a send()-capable object.
                self._sock = sock = _socket.socket(fileno=_os.dup(fd))
            except OSError:
                return False
        data = msgpack.packb({"t": "ntf", "i": 0, "m": method,
                              "d": payload}, use_bin_type=True)
        mutex = self._write_mutex
        if not mutex.acquire(blocking=False):
            return False
        try:
            if self._closed or self._outbuf:
                return False
            transport = self.writer.transport
            if transport is None or transport.get_write_buffer_size() > 0:
                return False
            view = memoryview(
                len(data).to_bytes(4, "little") + data)
            sent_any = False
            try:
                while view.nbytes:
                    try:
                        n = sock.send(view)
                    except (BlockingIOError, InterruptedError):
                        if not sent_any:
                            return False  # clean refusal; loop path takes it
                        # Mid-frame: the frame MUST complete or the
                        # stream corrupts. Wait for writability (tiny
                        # frames on a draining peer make this
                        # ~unreachable).
                        import select as _select

                        if not _select.select([], [sock], [], 2.0)[1]:
                            # Wedged socket with a half-written frame:
                            # the connection is unusable — abort it from
                            # the loop and report "sent" (it is dying
                            # either way; the peer's close handling owns
                            # cleanup).
                            if self._loop is not None:
                                self._loop.call_soon_threadsafe(
                                    transport.abort)
                            return True
                        continue
                    sent_any = True
                    view = view[n:]
            except (OSError, ValueError):
                # Broken pipe / socket closed under us (teardown race):
                # the read loop notices and owns the cleanup.
                return sent_any
            _tm().inc("ray_tpu_rpc_sent_bytes_total", 4 + len(data))
            return True
        finally:
            mutex.release()

    def write_buffer_empty(self) -> bool:
        """True when every flushed byte reached the kernel (the
        transport's user-space buffer is drained)."""
        if self._outbuf:
            return False
        transport = self.writer.transport
        return transport is None or transport.get_write_buffer_size() == 0

    async def _teardown(self):
        if self._closed:
            return
        # Flush frames _send already accepted before marking closed: a
        # graceful close in the same tick as a final reply/notify must
        # not drop it (the pre-coalescing code wrote synchronously).
        self._flush()
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.close()  # dup'ed fd only; transport unaffected
            except OSError:
                pass
            self._sock = None
        if self._pending:
            # Only losses that strand in-flight requests are recorded —
            # clean closes at shutdown are noise, not evidence.
            try:
                _fr().record("rpc", "conn_lost", severity="warn",
                             peer=self.name,
                             in_flight=len(self._pending))
            except Exception:
                pass  # interpreter teardown
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(self.name))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close:
            try:
                self.on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def close(self):
        if self._read_task:
            self._read_task.cancel()
        await self._teardown()

    @property
    def closed(self) -> bool:
        return self._closed


class Server:
    """Accepts connections; each gets the shared handler table."""

    def __init__(self, handlers: Dict[str, Handler], name: str = "server"):
        self.handlers = handlers
        self.name = name
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: list[Connection] = []
        self.on_connect: Optional[Callable[[Connection], None]] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        # Large read buffer: the data plane ships MiB chunk frames, and
        # the default 64KB StreamReader limit turns each into ~16 small
        # reads + wakeups.
        self._server = await asyncio.start_server(
            self._on_client, host, port, limit=READ_LIMIT)
        return self._server.sockets[0].getsockname()[1]

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self.handlers, name=f"{self.name}-peer")
        self.connections.append(conn)
        conn.on_close = lambda c: (
            self.connections.remove(c) if c in self.connections else None
        )
        conn.start()
        if self.on_connect:
            self.on_connect(conn)

    async def stop(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()


async def connect(host: str, port: int, handlers: Optional[Dict[str, Handler]] = None,
                  name: str = "client", timeout: float = 10.0) -> Connection:
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, limit=READ_LIMIT), timeout
    )
    conn = Connection(reader, writer, handlers or {}, name=name)
    conn.start()
    return conn


class EventLoopThread:
    """A dedicated thread running an asyncio loop, shared per process."""

    def __init__(self, name: str = "ray-tpu-io"):
        self.name = name
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        # Event-loop lag probe, armed a beat after start: rpc_stats
        # pulls in telemetry/config, which is not safe mid-bootstrap.
        self.loop.call_later(0.5, self._install_lag_probe)
        self.loop.run_forever()

    def _install_lag_probe(self):
        try:
            from ray_tpu.util import rpc_stats

            rpc_stats.install_probe(self.loop, self.name)
        except Exception:  # lint: allow-silent(lag probe is decoration; the loop must run regardless)
            pass

    def run(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def submit(self, coro):
        """Schedule without waiting; returns concurrent.futures.Future."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        async def _cancel_all():
            tasks = [
                t for t in asyncio.all_tasks(self.loop)
                if t is not asyncio.current_task()
            ]
            for t in tasks:
                t.cancel()

        try:
            self.run(_cancel_all(), timeout=2)
        except Exception:
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)
