"""Pip runtime environments: venv-per-dependency-hash with a refcounted
URI cache.

Reference: python/ray/_private/runtime_env/pip.py (virtualenv per env
hash, ``--system-site-packages`` so the base install is shared),
uri_cache.py (refcounted, size-bounded cache keyed by env URI) and
agent/runtime_env_agent.py:161 (create-or-reuse on task lease).

Design here: the env is materialized once per hash under the session
dir; a task whose ``runtime_env`` carries ``{"pip": [...]}`` gets the
venv's site-packages PREPENDED to ``sys.path`` for the task's duration
(workers are per-task-env processes in the reference; here the worker
injects/ejects the path, which gives the same import isolation for
pure-python deps without a respawn — two tasks with conflicting deps
run concurrently in different workers because the env hash is part of
the scheduling key).

Offline-friendly: ``pip_find_links`` (or RAY_TPU_PIP_FIND_LINKS) routes
installs through ``--no-index --find-links`` so air-gapped hosts (and
this repo's tests) install from local wheels.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import site
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
# env hash -> refcount (live tasks using it)
_refs: Dict[str, int] = {}


def env_hash(pip_packages: List[str]) -> str:
    canon = json.dumps(sorted(pip_packages)).encode()
    return hashlib.sha256(canon).hexdigest()[:16]


def _envs_root(session_dir: Optional[str] = None) -> str:
    base = session_dir or os.environ.get("RAY_TPU_SESSION_DIR") or "/tmp"
    return os.path.join(base, "runtime_envs", "pip")


def env_dir(pip_packages: List[str],
            session_dir: Optional[str] = None) -> str:
    return os.path.join(_envs_root(session_dir), env_hash(pip_packages))


def ensure_env(pip_packages: List[str],
               session_dir: Optional[str] = None,
               find_links: Optional[str] = None,
               timeout_s: float = 600.0) -> str:
    """Create (or reuse) the venv for this dependency set; returns its
    site-packages directory. Concurrent creators on one host coordinate
    through an atomic rename: the env is built in a temp dir and only
    the winner's rename lands (losers reuse it)."""
    target = env_dir(pip_packages, session_dir)
    sp = _site_packages(target)
    if os.path.exists(os.path.join(target, ".ready")):
        return sp
    os.makedirs(os.path.dirname(target), exist_ok=True)
    tmp = f"{target}.tmp.{os.getpid()}.{time.time_ns()}"
    try:
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             "--without-pip", tmp],
            check=True, capture_output=True, timeout=timeout_s)
        # Transitive deps install too (the reference's pip plugin
        # resolves full trees); offline hosts must stage EVERY needed
        # wheel in find_links.
        cmd = [sys.executable, "-m", "pip", "install",
               "--target", _site_packages(tmp)]
        links = (find_links
                 or os.environ.get("RAY_TPU_PIP_FIND_LINKS"))
        if links:
            cmd += ["--no-index", "--find-links", links]
        cmd += list(pip_packages)
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout_s)
        if out.returncode != 0:
            raise RuntimeError(
                f"pip install {pip_packages} failed: {out.stderr[-800:]}")
        with open(os.path.join(tmp, ".ready"), "w") as f:
            f.write(json.dumps(sorted(pip_packages)))
        try:
            os.rename(tmp, target)
        except OSError:
            # Lost the race: another creator landed first. Use theirs.
            shutil.rmtree(tmp, ignore_errors=True)
        return sp
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def _site_packages(venv_dir: str) -> str:
    v = sys.version_info
    return os.path.join(venv_dir, "lib", f"python{v.major}.{v.minor}",
                        "site-packages")


class PipEnvContext:
    """Task-scoped activation: prepend the env's site-packages, drop
    cached modules it shadows on exit so the next task resolves its own
    deps (the refcount keeps the env from being GCed while active)."""

    def __init__(self, pip_packages: List[str],
                 session_dir: Optional[str] = None):
        self.packages = list(pip_packages)
        self.hash = env_hash(pip_packages)
        self.site_dir = ensure_env(pip_packages, session_dir)
        self._shadowed: List[str] = []

    def __enter__(self):
        with _lock:
            _refs[self.hash] = _refs.get(self.hash, 0) + 1
        sys.path.insert(0, self.site_dir)
        site.addsitedir(self.site_dir)
        return self

    def __exit__(self, *exc):
        try:
            sys.path.remove(self.site_dir)
        except ValueError:
            pass
        # Evict modules imported from this env: a later task with a
        # DIFFERENT version of the same dep must re-import, not reuse.
        for name, mod in list(sys.modules.items()):
            origin = getattr(mod, "__file__", None) or ""
            if origin.startswith(self.site_dir):
                sys.modules.pop(name, None)
        with _lock:
            _refs[self.hash] = _refs.get(self.hash, 1) - 1
        return False


def gc_unused(session_dir: Optional[str] = None,
              max_envs: int = 8) -> List[str]:
    """Drop least-recently-created envs above the cache budget whose
    refcount is zero (reference: uri_cache.py's size-bounded eviction).
    Returns the deleted env dirs."""
    root = _envs_root(session_dir)
    try:
        entries = [os.path.join(root, d) for d in os.listdir(root)]
    except OSError:
        return []
    entries = [e for e in entries if os.path.isdir(e)]
    entries.sort(key=lambda e: os.path.getmtime(e))
    deleted = []
    with _lock:
        live = {h for h, n in _refs.items() if n > 0}
    while len(entries) > max_envs:
        victim = entries.pop(0)
        if os.path.basename(victim).split(".")[0] in live:
            continue
        shutil.rmtree(victim, ignore_errors=True)
        deleted.append(victim)
    return deleted
