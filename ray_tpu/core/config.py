"""Runtime configuration flags.

Equivalent of the reference's RAY_CONFIG X-macro table
(reference: src/ray/common/ray_config_def.h — 219 entries; ray_config.h:60):
every flag has a typed default, can be overridden per-process with a
``RAY_TPU_<NAME>`` environment variable, and can be overridden at init time
with a ``system_config`` dict passed to ``ray_tpu.init``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, fields


def _env_override(name: str, default):
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    ty = type(default)
    if ty is bool:
        return raw.lower() in ("1", "true", "yes")
    if ty in (int, float):
        return ty(raw)
    if ty in (dict, list):
        return json.loads(raw)
    return raw


@dataclass
class Config:
    # --- object store ---
    # Objects larger than this are stored in the node-wide shared-memory
    # store instead of the owner's in-process store (reference:
    # memory_store promotion threshold).
    max_direct_call_object_size: int = 100 * 1024
    # Shared-memory store capacity (bytes). 0 = auto (30% of system memory,
    # mirroring the reference's default_object_store_memory_proportion).
    object_store_memory: int = 0
    object_store_memory_proportion: float = 0.3
    # Spill-file directory override (default: <session dir>/spill).
    # Exported as RAY_TPU_OBJECT_SPILLING_DIR so workers share it.
    object_spilling_dir: str = ""
    # Soft high-water mark: LRU eviction of unpinned copies starts at
    # this fraction of shm capacity, keeping headroom before writers
    # overflow to disk spill files at the hard cap.
    object_spilling_threshold: float = 0.8
    # Back large objects with the native C++ arena (cpp/tpustore);
    # falls back to the python per-segment store if the build fails.
    use_native_object_store: bool = True

    # Echo worker stdout/stderr on the driver console (reference:
    # log_monitor.py streaming; RAY_TPU_LOG_TO_DRIVER=0 disables).
    log_to_driver: bool = True

    # --- memory monitor (reference: memory_monitor.h:52) ---
    # Kill a worker when host used/limit memory crosses this fraction.
    memory_monitor_enabled: bool = True
    memory_usage_threshold: float = 0.95

    # --- scheduler ---
    # Max worker leases requested in parallel per scheduling key
    # (reference: direct_task_transport.h:63 LeaseRequestRateLimiter).
    max_pending_lease_requests_per_scheduling_category: int = 10
    # Seconds an idle leased worker is kept before the lease is returned.
    idle_worker_lease_timeout_s: float = 0.25
    # Hybrid scheduling policy threshold (reference:
    # hybrid_scheduling_policy.cc spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Number of idle workers to keep prestarted per node.
    num_prestart_workers: int = 2
    # Max workers per node (0 = num_cpus).
    max_workers_per_node: int = 0

    # --- health / failure detection ---
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 5.0
    health_check_failure_threshold: int = 5

    # --- tasks ---
    task_default_max_retries: int = 3
    actor_default_max_restarts: int = 0
    # Max lineage entries retained per owner for object reconstruction
    # (reference: task_manager.h:202 max_lineage_bytes).
    max_lineage_entries: int = 10_000
    # Tasks pushed to one leased worker before its replies drain — hides
    # the push/reply RTT behind execution (reference:
    # max_tasks_in_flight_per_worker, direct_task_transport.h). Deeper
    # than the reference's 10: push frames amortize per-frame syscalls
    # and the pump distributes the queue EVENLY across leased workers,
    # so the cap is a ceiling, not the typical depth (imbalance stays
    # bounded by the even split).
    max_tasks_in_flight_per_worker: int = 64
    # Byte budget for retained creating-task specs used to reconstruct
    # lost shm objects (reference: task_manager.h:202 max_lineage_bytes).
    max_lineage_bytes: int = 64 * 1024 * 1024
    # How long a recovery resubmission may take to re-seal a lost object.
    object_recovery_timeout_s: float = 120.0
    # Persist control-plane tables (detached actors, PGs, KV, jobs) to
    # sqlite in the session dir so a restarted head recovers them
    # (reference: redis-backed GCS fault tolerance).
    gcs_fault_tolerance: bool = True

    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    rpc_max_message_size: int = 512 * 1024 * 1024
    # Long-poll pubsub batch window.
    pubsub_poll_timeout_s: float = 30.0

    # --- unified retry/backoff (core/retry.py RetryPolicy) ---
    # Every retry site in the RPC stack (task/actor pushes, GCS client
    # calls, object pulls, Serve assignment) shares this envelope:
    # exponential backoff with jitter, bounded attempts.
    rpc_retry_max_attempts: int = 5
    rpc_retry_base_delay_s: float = 0.05
    rpc_retry_max_delay_s: float = 2.0
    rpc_retry_multiplier: float = 2.0
    rpc_retry_jitter: float = 0.5

    # --- network fault injection (core/rpc.py FaultInjector) ---
    # Disabled by default; the idle plane costs one None check per
    # frame. RAY_TPU_FAULT_INJECTION_ENABLED=1 activates it;
    # RAY_TPU_FAULT_INJECTION_RULES takes a JSON list of rule dicts,
    # e.g. '[{"action": "drop", "method": "push_tasks",
    # "probability": 0.05}]'.
    fault_injection_enabled: bool = False
    fault_injection_seed: int = 0
    fault_injection_rules: str = ""

    # --- node-death grace (core/gcs.py) ---
    # An agent health-channel close marks the node SUSPECT for this
    # window instead of declaring it dead; the agent reconnects with
    # backoff and reattaches (0 restores instant declare-dead).
    gcs_node_death_grace_s: float = 3.0

    # --- object transfer ---
    # Full sweeps over the holder list per pull (transient drops heal
    # instead of surfacing ObjectLostError).
    object_pull_max_attempts: int = 3

    # --- device-native object plane (core/device_objects.py) ---
    # Store qualifying jax.Array leaves of put() values as per-shard
    # device buffers + a sharding descriptor instead of a pickled host
    # blob; get() returns them by reference in the producing process and
    # reassembles via per-shard pulls elsewhere. Off restores the
    # host-numpy path everywhere.
    device_object_plane_enabled: bool = True
    # Arrays below this stay on the host path (tiny scalars aren't worth
    # descriptor + manifest traffic).
    device_object_min_bytes: int = 1024
    # Shards pulled concurrently per get(): bounds host staging memory
    # at concurrency x shard size, never the whole array.
    device_shard_pull_concurrency: int = 4

    # --- metrics / tracing ---
    # Built-in ray_tpu_* metrics plane (util/telemetry.py). On by
    # default: instruments RPC, retry, scheduler, object, GCS, Serve and
    # train hot paths; RAY_TPU_METRICS_ENABLED=0 turns it all off.
    metrics_enabled: bool = True
    # Per-RPC client/server spans (core/rpc.py). Off by default — one
    # span pair per request is too hot for production; turn on to see
    # individual control-plane calls inside a trace.
    trace_rpc: bool = False
    # Throttle window for pushing a process's metrics registry to the
    # head KV (util/metrics.py _maybe_push).
    metrics_report_interval_s: float = 2.0
    # Task-event buffer flush (reference: task_event_buffer.h).
    task_events_report_interval_s: float = 1.0
    task_events_max_buffer_size: int = 10_000
    # A pushed metrics snapshot older than this is stale: the summary
    # surfaces flag it instead of merging it as current, and gauge
    # carry-forward in history window queries stops.
    metrics_staleness_s: float = 15.0

    # --- cluster health plane (core/health.py) ---
    # Head-side bounded per-series time-series over metrics pushes
    # (util/metrics_history.py). On by default: append cost is
    # O(changed series) per push and memory is hard-capped below.
    metrics_history_enabled: bool = True
    # Fine ring length per series (one point per *change*, so at the 2s
    # push cadence 240 points cover >= 8 minutes of a busy series).
    metrics_history_recent_points: int = 240
    # Coarse ring: one point per interval, extending coverage to hours
    # (360 x 30s = 3h) behind the fine ring.
    metrics_history_coarse_points: int = 360
    metrics_history_coarse_interval_s: float = 30.0
    # Hard byte budget for the whole store; least-recently-updated
    # series are evicted whole past this (eviction counter exported).
    metrics_history_max_bytes: int = 16 * 1024 * 1024
    # Per-metric series-count cap: a single metric name may hold at
    # most this many tag sets before its least-recently-updated series
    # are evicted (high-cardinality tag explosions must not LRU-thrash
    # every other metric out of the byte budget above).
    metrics_history_max_series_per_metric: int = 64
    # --- control-plane load observatory (util/rpc_stats.py) ---
    # Cadence of the self-scheduling event-loop lag probe installed on
    # every process loop (head / agent / worker / driver); lag past the
    # stall threshold leaves an rpc/loop_stall flight event.
    event_loop_probe_interval_s: float = 0.25
    event_loop_stall_threshold_s: float = 0.5
    # SLO/alert rule engine (util/alerts.py) over the history store.
    alerts_enabled: bool = True
    # Min seconds between rule sweeps (pushes arrive per-proc, so the
    # raw hook cadence is n_procs / report_interval).
    alerts_eval_interval_s: float = 1.0

    # --- flight recorder / debug plane (util/flight_recorder.py) ---
    # Always-on per-process ring of structured decision events (scheduler
    # wait reasons, object lifecycle, retries/breakers, node states,
    # gang health). On by default: the idle cost is one deque append;
    # RAY_TPU_FLIGHT_RECORDER_ENABLED=0 turns it off.
    flight_recorder_enabled: bool = True
    # Events retained per process (a fixed-size ring; older entries are
    # overwritten).
    flight_recorder_capacity: int = 2048

    # --- live profiling plane (util/profiler.py) ---
    # Always-on low-Hz background sampler: folded-stack snapshots into
    # <session>/profile/, a profile:<pid> timeline lane, and the
    # overhead gauge. Off by default — the on-demand `ray_tpu profile`
    # surface needs no standing cost; turn this on for soak triage.
    profiler_continuous_enabled: bool = False
    # Sampling rate of the continuous mode (the on-demand rate is a CLI
    # flag). 10 Hz keeps measured overhead well under the bound below.
    profiler_continuous_hz: float = 10.0
    # How often the continuous sampler rewrites its snapshot file and
    # publishes its timeline window.
    profiler_snapshot_interval_s: float = 5.0
    # Measured-overhead self-check: when sampling time / wall time
    # crosses this, the continuous sampler halves its rate.
    profiler_max_overhead_ratio: float = 0.02
    # Retention for the continuous sampler's snapshot directory
    # (<session>/profile/): oldest files beyond either cap are deleted
    # after each snapshot rewrite, so a long soak can't fill the disk.
    # 0 disables the corresponding bound.
    profiler_snapshot_max_files: int = 64
    profiler_snapshot_max_bytes: int = 32 * 1024 * 1024

    # --- device trace plane (util/device_trace.py) ---
    # Hard cap on one jax.profiler capture window; requests above it
    # are clamped (a capture holds the per-process capture lock for
    # its whole duration).
    device_trace_max_duration_s: float = 60.0
    # A trace file above this is dropped with an error instead of
    # shipped over RPC / retained on disk (device traces grow with
    # ops x duration; the fan-out reply must stay bounded).
    device_trace_max_trace_bytes: int = 64 * 1024 * 1024
    # Retention for <session>/device_trace/ raw trace files (same
    # oldest-first policy as the profiler snapshot dir; 0 disables).
    device_trace_retain_files: int = 8
    device_trace_retain_bytes: int = 256 * 1024 * 1024

    # --- experiment-state journal (core/health.py) ---
    # Periodically persist the head's metrics-history rings + open
    # alert state to <session>/health_journal/ and reload them on head
    # start, so a restarted driver recovers metrics_history and alert
    # continuity instead of starting cold.
    health_journal_enabled: bool = True
    health_journal_interval_s: float = 30.0

    # --- lockdep witness (util/locks.py) ---
    # Debug-mode instrumented locks: record cross-thread lock
    # acquisition order, detect lock-order inversions (ABBA) the first
    # time a cycle closes. Off in production (make_lock hands out plain
    # threading locks); the chaos/test lanes turn it on with
    # RAY_TPU_LOCKDEP=1 before the cluster comes up.
    lockdep_enabled: bool = False

    # --- workers ---
    # Spawn workers by forking a preimported forkserver process instead
    # of a cold interpreter per worker (core/forkserver.py). POSIX only;
    # falls back to Popen on any error.
    worker_forkserver: bool = True

    # --- serve ---
    # Router -> controller control calls (snapshot refresh, pending-
    # request reports).
    serve_control_timeout_s: float = 30.0
    # How long the router waits for scale-from-zero to bring a replica
    # up before retrying/failing an assignment.
    serve_scale_wait_timeout_s: float = 30.0
    # Assignment attempts per request (replica death between refreshes).
    serve_assign_max_attempts: int = 3
    # DeploymentResponse default resolve/result timeout.
    serve_handle_resolve_timeout_s: float = 60.0
    # Per-replica circuit breaker: consecutive send failures before the
    # replica is shed, and how long it stays shed before a probe.
    serve_cb_failure_threshold: int = 3
    serve_cb_reset_timeout_s: float = 5.0
    # Streaming responses: max wait between consecutive chunks before
    # the proxy aborts the stream with a terminal error event (a hung
    # replica mid-stream keeps its connection alive, so only an
    # inter-chunk deadline catches it).
    serve_stream_chunk_timeout_s: float = 120.0
    # Request-body cap for the HTTP proxy. Bodies (including chunked /
    # streamed uploads — long prompts) are accumulated incrementally
    # and rejected with an honest 413 the moment they cross this bound,
    # so an oversized upload can never balloon proxy memory.
    serve_max_request_body_bytes: int = 64 * 1024 * 1024

    # --- logging ---
    log_dir: str = ""

    def __post_init__(self):
        for f in fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def apply_system_config(self, system_config: dict | None):
        if not system_config:
            return
        for key, value in system_config.items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown system config key: {key}")
            setattr(self, key, value)


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config()
    return _global_config


def reset_config():
    global _global_config
    _global_config = None
