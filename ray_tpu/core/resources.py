"""Resource sets and node resource accounting.

Reference: src/ray/common/scheduling/resource_set.h and
cluster_resource_data.h. Resources are name → float maps with fixed-point
semantics (we quantize to 1e-4 like the reference's FixedPoint) so that
fractional resources (e.g. ``num_cpus=0.5``) compose without float drift.

TPU specifics: a node exposes ``TPU`` (chip count) plus, when it is part of
a pod slice, a synthetic gang resource ``TPU-<topology>-head`` on the slice's
first host (reference: python/ray/_private/accelerators/tpu.py:335,382) so
that slice-wide placement groups can anchor on one host per slice.
"""

from __future__ import annotations

from typing import Dict, Optional

QUANTUM = 10_000  # 1e-4 resolution


def _to_fp(value: float) -> int:
    return round(value * QUANTUM)


def _from_fp(value: int) -> float:
    return value / QUANTUM


class ResourceSet:
    """Immutable-ish fixed-point resource map."""

    __slots__ = ("_fp",)

    def __init__(self, resources: Optional[Dict[str, float]] = None):
        self._fp: Dict[str, int] = {}
        if resources:
            for name, value in resources.items():
                fp = _to_fp(value)
                if fp < 0:
                    raise ValueError(f"negative resource {name}={value}")
                if fp > 0:
                    self._fp[name] = fp

    @classmethod
    def _from_fp_map(cls, fp: Dict[str, int]) -> "ResourceSet":
        rs = cls()
        rs._fp = {k: v for k, v in fp.items() if v > 0}
        return rs

    def to_dict(self) -> Dict[str, float]:
        return {k: _from_fp(v) for k, v in self._fp.items()}

    def get(self, name: str) -> float:
        return _from_fp(self._fp.get(name, 0))

    def is_empty(self) -> bool:
        return not self._fp

    def is_subset_of(self, other: "ResourceSet") -> bool:
        return all(other._fp.get(k, 0) >= v for k, v in self._fp.items())

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        fp = dict(self._fp)
        for k, v in other._fp.items():
            fp[k] = fp.get(k, 0) + v
        return ResourceSet._from_fp_map(fp)

    def __sub__(self, other: "ResourceSet") -> "ResourceSet":
        fp = dict(self._fp)
        for k, v in other._fp.items():
            nv = fp.get(k, 0) - v
            if nv < 0:
                raise ValueError(f"resource {k} would go negative")
            fp[k] = nv
        return ResourceSet._from_fp_map(fp)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._fp == other._fp

    def __hash__(self):
        return hash(tuple(sorted(self._fp.items())))

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


class NodeResources:
    """Total + available resources of one node, with acquire/release."""

    def __init__(self, total: ResourceSet):
        self.total = total
        self.available = total

    def can_fit(self, request: ResourceSet) -> bool:
        return request.is_subset_of(self.available)

    def feasible(self, request: ResourceSet) -> bool:
        """Could ever fit, even if currently busy."""
        return request.is_subset_of(self.total)

    def acquire(self, request: ResourceSet) -> bool:
        if not self.can_fit(request):
            return False
        self.available = self.available - request
        return True

    def release(self, request: ResourceSet):
        self.available = self.available + request
        # Clamp against double-release bugs.
        for k, v in self.available._fp.items():
            cap = self.total._fp.get(k, 0)
            if v > cap:
                self.available._fp[k] = cap

    def utilization(self) -> float:
        """Critical-resource utilization in [0, 1] (for hybrid policy)."""
        best = 0.0
        for k, total in self.total._fp.items():
            if total <= 0:
                continue
            used = total - self.available._fp.get(k, 0)
            best = max(best, used / total)
        return best
