"""Job submission: run entrypoint commands on the cluster.

Reference: dashboard/modules/job/job_manager.py:525 (JobManager spawns a
per-job JobSupervisor actor (:140) that runs the entrypoint as a
subprocess) and sdk.py:39 (JobSubmissionClient). Job status and logs
live in the head KV so any driver can query them; the entrypoint
subprocess gets RAY_TPU_ADDRESS so it attaches to the same cluster.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _JobSupervisor:
    """Per-job async actor: runs the entrypoint subprocess, streams logs
    to a file, records status in the head KV (reference: JobSupervisor).
    Async so stop() can interleave with a blocking run(); exits itself
    once the job reaches a terminal state (the reference supervisor does
    the same) so finished jobs hold no resources."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[dict], cluster_address: str,
                 log_dir: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.cluster_address = cluster_address
        os.makedirs(log_dir, exist_ok=True)
        self.log_path = os.path.join(log_dir, f"job-{job_id}.log")
        self.proc: Optional[subprocess.Popen] = None
        self._set_status(JobStatus.PENDING)

    def _kv_submit(self, op: str, **kw):
        """Fire-and-forget KV write. This actor is async: its methods
        run ON the worker event loop, so a blocking loop_thread.run here
        would deadlock the loop against itself."""
        from ray_tpu.core.object_ref import get_core_worker

        cw = get_core_worker()
        cw.loop_thread.submit(cw.head.call(op, kw))

    def _set_status(self, status: str, message: str = ""):
        payload = {
            "job_id": self.job_id,
            "status": status,
            "message": message,
            "entrypoint": self.entrypoint,
            "log_path": self.log_path,
            "ts": time.time(),
        }
        self._kv_submit("kv_put", ns="jobs",
                        key=f"job:{self.job_id}".encode(),
                        value=json.dumps(payload).encode(),
                        overwrite=True)

    async def run(self) -> str:
        import asyncio

        import ray_tpu

        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self.cluster_address
        env.update(self.runtime_env.get("env_vars", {}))
        cwd = self.runtime_env.get("working_dir") or None
        self._set_status(JobStatus.RUNNING)
        loop = asyncio.get_event_loop()
        try:
            with open(self.log_path, "ab") as log_file:
                self.proc = subprocess.Popen(
                    self.entrypoint, shell=True, env=env, cwd=cwd,
                    stdout=log_file, stderr=subprocess.STDOUT)
                # Block off-loop so stop() stays responsive.
                code = await loop.run_in_executor(None, self.proc.wait)
        except Exception as e:
            self._set_status(JobStatus.FAILED,
                             f"{type(e).__name__}: {e}")
            ray_tpu.actor_exit()
        if code == 0:
            self._set_status(JobStatus.SUCCEEDED)
        elif code < 0:
            self._set_status(JobStatus.STOPPED,
                             f"terminated by signal {-code}")
        else:
            self._set_status(JobStatus.FAILED, f"exit code {code}")
        # Terminal: release this supervisor's resources.
        ray_tpu.actor_exit()

    async def stop(self) -> bool:
        import asyncio

        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            loop = asyncio.get_event_loop()
            try:
                await asyncio.wait_for(
                    loop.run_in_executor(None, self.proc.wait), 10)
            except asyncio.TimeoutError:
                self.proc.kill()
            return True
        return False

    async def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Reference: dashboard/modules/job/sdk.py:39 — submit/status/logs/
    stop/list against the connected cluster."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address or "auto")
        from ray_tpu import api as _api
        from ray_tpu.core.object_ref import get_core_worker

        self._cw = get_core_worker()
        if _api._global_node is not None:
            self._address = f"127.0.0.1:{_api._global_node.port}"
            self._log_dir = os.path.join(
                _api._global_node.session_dir, "logs")
        else:
            self._address = address or _api._read_cluster_address()
            self._log_dir = os.path.join(
                os.path.expanduser("~/.ray_tpu_jobs"))
        self._supervisors: Dict[str, Any] = {}

    def _kv(self, op: str, **kw):
        return self._cw.loop_thread.run(self._cw.head.call(op, kw))

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        supervisor = (ray_tpu.remote(_JobSupervisor)
                      .options(num_cpus=0.1,
                               name=f"_job_supervisor:{job_id}",
                               lifetime="detached")
                      .remote(job_id, entrypoint, runtime_env,
                              self._address, self._log_dir))
        # Fire the run; result arrives asynchronously.
        supervisor.run.remote()
        self._supervisors[job_id] = supervisor
        return job_id

    def get_job_info(self, job_id: str) -> dict:
        reply = self._kv("kv_get", ns="jobs",
                         key=f"job:{job_id}".encode())
        blob = reply.get("value")
        if not blob:
            raise ValueError(f"no job {job_id!r}")
        return json.loads(bytes(blob).decode())

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id)["status"]

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        try:
            with open(info["log_path"]) as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def stop_job(self, job_id: str) -> bool:
        sup = self._supervisors.get(job_id)
        if sup is None:
            try:
                sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
            except Exception:
                return False
        try:
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            # The supervisor exits itself once the job goes terminal; a
            # death racing the stop reply means the stop took effect.
            try:
                return self.get_job_status(job_id) in (
                    JobStatus.STOPPED, JobStatus.FAILED,
                    JobStatus.SUCCEEDED)
            except ValueError:
                return False

    def list_jobs(self) -> List[dict]:
        reply = self._kv("kv_keys", ns="jobs", prefix=b"job:")
        out = []
        for key in reply.get("keys", []):
            blob = self._kv("kv_get", ns="jobs", key=key).get("value")
            if blob:
                out.append(json.loads(bytes(blob).decode()))
        return sorted(out, key=lambda j: j["ts"])

    def wait_until_finish(self, job_id: str, timeout: float = 300
                          ) -> str:
        deadline = time.time() + timeout
        terminal = {JobStatus.SUCCEEDED, JobStatus.FAILED,
                    JobStatus.STOPPED}
        status = JobStatus.PENDING
        while time.time() < deadline:
            try:
                status = self.get_job_status(job_id)
            except ValueError:
                # Supervisor actor still starting; its constructor
                # writes the PENDING record once the worker is up.
                status = JobStatus.PENDING
            if status in terminal:
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {status}")
