"""ray_tpu.job — job submission (reference: dashboard/modules/job)."""

from ray_tpu.job.job_manager import (
    JobStatus,
    JobSubmissionClient,
)

__all__ = ["JobStatus", "JobSubmissionClient"]
