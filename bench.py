"""Headline benchmark: flagship-model training MFU on the local TPU chip.

Prints ONE JSON line:
  {"metric": "train_mfu", "value": <fraction>, "unit": "mfu",
   "vs_baseline": <value / 0.40>}

Baseline: the north-star target from BASELINE.json — "Ray Train Llama-2-7B
SPMD ≥40% MFU" (the reference publishes no ML-workload numbers in-repo;
0.40 MFU is its stated bar, see BASELINE.md). We measure a single-chip
Llama-family train step (bf16 activations, MXU-aligned 128-dim heads,
XLA fused attention at this sequence length, full remat, adamw) sized
for one v5e chip and report model-FLOPs utilization against the chip's
peak bf16 throughput.
"""

from __future__ import annotations

import json
import time


def peak_flops_per_chip() -> float:
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12  # bf16
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    return 197e12  # assume v5e-class


def model_flops_per_step(cfg, batch: int, seq: int) -> float:
    """6*N per token for matmul params + attention score/value matmuls."""
    h = cfg.hidden_size
    matmul_params = cfg.num_params() - cfg.vocab_size * h  # minus embed gather
    tokens = batch * seq
    dense = 6.0 * matmul_params * tokens
    attn = 12.0 * cfg.num_layers * seq * h * tokens
    return dense + attn


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import Llama, LlamaConfig
    from ray_tpu.parallel import MeshConfig, create_mesh
    from ray_tpu.train.spmd import (
        make_causal_lm_batch_loss,
        make_sharded_train,
    )

    on_tpu = jax.default_backend() != "cpu"
    if on_tpu:
        # Tuned on v5e: head_dim=128 (MXU lane-aligned; 8 heads at
        # h=1024) + the Pallas flash kernels (fwd + blocked bwd, tuned
        # 256/512 tiles — r5) + full remat. Measured 0.488 MFU vs
        # 0.438 with XLA attention (r4) and 0.225 for the initial
        # 16-head config.
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=4096,
            num_layers=24, num_heads=8, num_kv_heads=8, max_seq_len=1024,
            scan_layers=True, remat=True, attention_impl="flash",
        )
        batch, seq, iters = 16, 1024, 8
    else:  # CPU smoke fallback so the bench never hard-fails
        cfg = LlamaConfig.tiny()
        batch, seq, iters = 2, 64, 2

    mesh = create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    model = Llama(cfg)
    tokens = jnp.ones((batch, seq), jnp.int32)
    example = {"inputs": tokens}
    init, step, _ = make_sharded_train(
        model, optax.adamw(1e-4, weight_decay=0.0), mesh, example,
        make_causal_lm_batch_loss(),
    )
    state = init(jax.random.PRNGKey(0))
    # Warmup/compile. NB: block_until_ready is unreliable on the tunneled
    # axon platform; a host scalar fetch is the only dependable sync.
    for _ in range(2):
        state, metrics = step(state, example)
        float(metrics["loss"])
    # Chained steps with one trailing sync: a per-step host fetch would
    # charge a tunnel round trip to every step (~8% on axon).
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, example)
    float(metrics["loss"])  # forces completion of the whole chain
    dt = (time.perf_counter() - t0) / iters
    flops = model_flops_per_step(cfg, batch, seq)
    achieved = flops / dt
    mfu = achieved / peak_flops_per_chip()
    _run_core_bench()
    _run_serve_stream_bench()
    print(json.dumps({
        "metric": "train_mfu",
        "value": round(mfu, 4),
        "unit": "mfu",
        "vs_baseline": round(mfu / 0.40, 4),
    }))


def _run_core_bench():
    """Side artifact: core control-plane throughput (tasks/s, actor
    calls/s, store bandwidth) written to BENCH_CORE.json so regressions
    on the task path are visible per round (BASELINE.md microbenchmark
    table is the floor). Never allowed to break the headline metric."""
    import os
    import subprocess
    import sys

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_CORE.json")
    try:
        subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.microbenchmark",
             "--json", out],
            timeout=300, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except Exception:
        pass


def _run_serve_stream_bench():
    """Side artifact: serve streaming quality (TTFT, inter-chunk
    p50/p99, chunks/s at N concurrent streams) written to
    BENCH_SERVE_STREAM.json — the perf trajectory covers the streaming
    plane from day one. Never allowed to break the headline metric."""
    import os
    import subprocess
    import sys

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_SERVE_STREAM.json")
    try:
        subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.serve_stream_bench",
             "--json", out],
            timeout=300, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
    except Exception:
        pass


def _run_serve_cb_bench():
    """`bench.py serve-cb`: the continuous-batching load lane — 1k+
    concurrent SSE streams through the HTTP proxy against an engine
    deployment (p50/p99 TTFT, inter-chunk latency, chunks/s, shed
    rate). Writes BENCH_SERVE_CB.json plus
    BENCH_SERVE_CB_HISTORY.json (the head's metrics time-series +
    alert episodes over the run — the trajectory, not just the
    endpoint)."""
    import os
    import subprocess
    import sys

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_SERVE_CB.json")
    subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.serve_cb_bench",
         "--json", out],
        timeout=1200, check=True,
        # Echoing 1k streams' proxy access logs to the driver would
        # dominate the measurement.
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "RAY_TPU_LOG_TO_DRIVER": "0"},
    )


def _run_control_plane_bench():
    """`bench.py control-plane`: the control-plane load lane — a
    25-50 logical-node fake cluster driving registration + task +
    actor + pubsub + KV churn, then the load observatory read back
    out. Writes BENCH_CONTROL_PLANE.json (per-handler p50/p99
    server-side timings, event-loop lag, fan-out amplification
    factors)."""
    import os
    import subprocess
    import sys

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_CONTROL_PLANE.json")
    subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.control_plane_bench",
         "--json", out],
        timeout=1200, check=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "RAY_TPU_LOG_TO_DRIVER": "0"},
    )


def _run_transfer_device_bench():
    """`bench.py transfer-device`: the device-plane transfer lane —
    1 GiB sharded jax.Array, shared-device zero-copy get + cross-process
    per-shard pull, vs the r05 host-bounce baseline. Writes
    BENCH_TRANSFER_r06.json."""
    import os
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(here, "BENCH_TRANSFER_r06.json")
    baseline = os.path.join(here, "BENCH_TRANSFER_r05.json")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.device_transfer_bench",
         "--out", out, "--baseline", baseline],
        timeout=1200, check=True, env=env,
    )


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "transfer-device":
        _run_transfer_device_bench()
    elif len(sys.argv) > 1 and sys.argv[1] == "serve-cb":
        _run_serve_cb_bench()
    elif len(sys.argv) > 1 and sys.argv[1] == "control-plane":
        _run_control_plane_bench()
    else:
        main()
