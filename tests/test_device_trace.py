"""Device-trace plane: parser units (synthetic chrome-trace fixtures,
wall-clock anchoring, compile/execute split, step attribution, corrupt
input), the phase-window recorder, output rotation, the in-process
capture e2e (a jitted step traced under JAX_PLATFORMS=cpu), and the
cluster lanes (fan-out capture of a worker running an instrumented
step, merged host+device timeline, debug-bundle section, SIGKILL
mid-capture chaos).

Unit tests run first — they must see NO cluster; the module-scoped
cluster fixture only spins up for the e2e half.
"""

import gzip
import json
import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import device_trace


def _wait_for(predicate, timeout=30.0, interval=0.05, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def _mk_trace(events) -> bytes:
    return gzip.compress(json.dumps(
        {"displayTimeUnit": "ns", "traceEvents": events}).encode())


# A synthetic jax.profiler trace: a device process with XLA ops (one
# nesting pair), a codegen thread, and a `$`-prefixed python-tracer
# event that sits at the trace-clock origin (ts=0 == start_trace).
_SYNTH_EVENTS = [
    {"ph": "M", "pid": 1, "name": "process_name",
     "args": {"name": "/device:CPU:0"}},
    {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
     "args": {"name": "tf_XLATfrtCpuClient/1"}},
    {"ph": "M", "pid": 2, "name": "process_name",
     "args": {"name": "python"}},
    {"ph": "M", "pid": 2, "tid": 20, "name": "thread_name",
     "args": {"name": "tf_xla-cpu-llvm-codegen/2"}},
    {"ph": "X", "pid": 3, "tid": 30, "ts": 0, "dur": 100,
     "name": "$profiler.py:10 start_trace"},
    # fusion.1 [1000, 2000) with dot.2 [1200, 1600) nested inside:
    # self times 600 / 400.
    {"ph": "X", "pid": 1, "tid": 10, "ts": 1000, "dur": 1000,
     "name": "fusion.1",
     "args": {"hlo_op": "fusion.1", "hlo_module": "jit_step"}},
    {"ph": "X", "pid": 1, "tid": 10, "ts": 1200, "dur": 400,
     "name": "dot.2",
     "args": {"hlo_op": "dot.2", "hlo_module": "jit_step"}},
    {"ph": "X", "pid": 1, "tid": 10, "ts": 3000, "dur": 500,
     "name": "sine.3",
     "args": {"hlo_op": "sine.3", "hlo_module": "jit_step"}},
    # codegen work (no hlo args; classified by thread name).
    {"ph": "X", "pid": 2, "tid": 20, "ts": 1000, "dur": 800,
     "name": "LlvmCompile"},
    # outside every phase window -> unattributed.
    {"ph": "X", "pid": 1, "tid": 10, "ts": 9000, "dur": 200,
     "name": "tanh.4", "args": {"hlo_op": "tanh.4"}},
]

_T0 = 100.0
_SYNTH_WINDOWS = [
    {"phase": "compile", "t0": _T0 + 0.0005, "t1": _T0 + 0.002,
     "step": 7, "rank": 1},
    {"phase": "step", "t0": _T0 + 0.002, "t1": _T0 + 0.004,
     "step": 7, "rank": 1},
]


# ---------------------------------------------------------------------------
# parser units
# ---------------------------------------------------------------------------

def test_parse_trace_ops_split_and_anchor():
    out = device_trace.parse_trace(_mk_trace(_SYNTH_EVENTS),
                                   t0_wall=_T0,
                                   windows=_SYNTH_WINDOWS, pid=42)
    assert not out.get("error")
    s = out["summary"]
    assert s["device_events"] == 4
    assert s["compile_events"] == 1
    assert s["python_events_dropped"] == 1
    # self-time nesting: fusion 600, dot 400, sine 500, tanh 200.
    assert s["execute_us"] == 1700.0
    assert s["compile_us"] == 800.0
    assert s["unattributed_us"] == 200.0
    # demangled, sorted by self device time.
    by_op = {r["op"]: r for r in out["ops"]}
    assert set(by_op) == {"fusion", "dot", "sine", "tanh"}
    assert by_op["fusion"]["self_us"] == 600.0
    assert by_op["fusion"]["total_us"] == 1000.0
    assert by_op["dot"]["self_us"] == 400.0
    assert [r["op"] for r in out["ops"][:2]] == ["fusion", "sine"]
    # lanes: wall-clock anchored at t0_wall + (ts - base)/1e6, with the
    # python event at ts=0 as the base even though it was dropped.
    dev = [ln for ln in out["lanes"] if ln["cat"] == "device:42"]
    comp = [ln for ln in out["lanes"] if ln["cat"] == "device:42:compile"]
    assert len(dev) == 4 and len(comp) == 1
    fusion_lane = next(ln for ln in dev if ln["name"] == "fusion.1")
    assert fusion_lane["ts"] == pytest.approx(_T0 + 0.001)
    assert fusion_lane["dur"] == pytest.approx(0.001)
    assert fusion_lane["args"]["hlo_module"] == "jit_step"


def test_parse_trace_step_attribution():
    out = device_trace.parse_trace(_mk_trace(_SYNTH_EVENTS),
                                   t0_wall=_T0,
                                   windows=_SYNTH_WINDOWS, pid=42)
    (row,) = out["steps"]
    assert row["rank"] == 1 and row["step"] == 7
    # compile window catches fusion+dot (device time inside a compile
    # phase counts as compile) plus the codegen event: 0.6+0.4+0.8 ms.
    assert row["compile_ms"] == pytest.approx(1.8)
    # the step window catches sine's 0.5 ms of self time.
    assert row["execute_ms"] == pytest.approx(0.5)
    assert row["wall_ms"] == pytest.approx(3.5)
    assert row["gap_ms"] == pytest.approx(3.5 - 1.8 - 0.5)
    assert ["sine", 0.5] in row["top_ops"]


@pytest.mark.parametrize("blob", [
    b"not a gzip at all",
    gzip.compress(b"{not json"),
    gzip.compress(b'{"traceEvents": 7}'),
    _mk_trace(_SYNTH_EVENTS)[:40],  # truncated mid-stream
])
def test_parse_trace_corrupt_input_structured_error(blob):
    out = device_trace.parse_trace(blob)
    assert out["error"]
    assert out["ops"] == [] and out["steps"] == [] and out["lanes"] == []


def test_demangle():
    assert device_trace._demangle("%fusion.123") == "fusion"
    assert device_trace._demangle("dot_general.4") == "dot_general"
    assert device_trace._demangle("custom-call") == "custom-call"


# ---------------------------------------------------------------------------
# phase-window recorder
# ---------------------------------------------------------------------------

def test_phase_window_step_numbering():
    device_trace.reset_phase_windows_for_testing()
    try:
        with device_trace.step_phase("compile", rank=3):
            time.sleep(0.01)
        for _ in range(2):
            with device_trace.step_phase("step", rank=3):
                time.sleep(0.01)
        assert device_trace.current_step() == 2
        wins = device_trace.phase_windows(0.0, time.time() + 1.0)
        assert [(w["phase"], w["step"]) for w in wins] == [
            ("compile", 0), ("step", 0), ("step", 1)]
        assert all(w["rank"] == 3 for w in wins)
        assert all(w["t1"] > w["t0"] for w in wins)
        # range filter: a window entirely in the past is excluded.
        assert device_trace.phase_windows(time.time() + 10,
                                          time.time() + 20) == []
    finally:
        device_trace.reset_phase_windows_for_testing()


# ---------------------------------------------------------------------------
# output rotation (satellite: bounded snapshot/trace dirs)
# ---------------------------------------------------------------------------

def test_rotate_dir_bounds_files_and_bytes(tmp_path):
    from ray_tpu.util.profiler import rotate_dir

    d = str(tmp_path)
    for i in range(10):
        p = os.path.join(d, f"f{i}")
        with open(p, "wb") as f:
            f.write(b"x" * 100)
        os.utime(p, (1000 + i, 1000 + i))  # f9 newest
    assert rotate_dir(d, max_files=4) == 6
    assert sorted(os.listdir(d)) == ["f6", "f7", "f8", "f9"]
    # byte cap: 100B each, cap 250 -> the 2 newest survive.
    assert rotate_dir(d, max_bytes=250) == 2
    assert sorted(os.listdir(d)) == ["f8", "f9"]
    # keep= pins a file regardless of age and counts against the cap.
    assert rotate_dir(d, max_files=1,
                      keep=(os.path.join(d, "f8"),)) == 1
    assert os.listdir(d) == ["f8"]
    # caps of 0 disable rotation entirely.
    assert rotate_dir(d) == 0


def test_continuous_sampler_snapshot_dir_rotated(tmp_path,
                                                 monkeypatch):
    """The continuous host sampler's snapshot dir stays bounded by the
    profiler_snapshot_* flags (stale snapshots from dead pids are the
    files rotation exists to delete)."""
    from ray_tpu.core.config import Config
    from ray_tpu.util import profiler, telemetry

    d = str(tmp_path / "profile")
    os.makedirs(d)
    for i in range(6):
        p = os.path.join(d, f"profile-{4000 + i}.folded")
        with open(p, "w") as f:
            f.write("stale 1\n" * 10)
        os.utime(p, (2000 + i, 2000 + i))
    cfg = Config()
    cfg.profiler_snapshot_max_files = 3
    cfg.profiler_snapshot_max_bytes = 0
    monkeypatch.setattr(profiler, "_config", lambda: cfg)
    s = profiler.ContinuousSampler(out_dir=d)
    s._snapshot(time.monotonic(), 0.1, 0, telemetry)
    names = os.listdir(d)
    # own snapshot (pinned via keep=) + the 2 newest stale survivors.
    assert os.path.basename(s.snapshot_path) in names
    assert len(names) <= 3
    assert "profile-4000.folded" not in names
    assert "profile-4001.folded" not in names


# ---------------------------------------------------------------------------
# memory census
# ---------------------------------------------------------------------------

def test_device_memory_census_cpu_null_stats():
    from ray_tpu.core import device_objects as dobj

    census = device_trace.device_memory_census()
    assert "devices_error" not in census
    assert len(census["devices"]) >= 1
    # CPU backend has no memory_stats: graceful null, never an error.
    assert all(d["memory_stats"] is None for d in census["devices"])
    assert all(d["platform"] == "cpu" for d in census["devices"])

    # Live-array census counts registry entries by sharding kind.
    entry = dobj._ObjectEntry(owned=True)
    entry.leaves[0] = dobj._LeafEntry(
        desc={"kind": "single"}, nbytes=4096)
    with dobj._registry_lock:
        dobj._registry["census-test"] = entry
    try:
        census = device_trace.device_memory_census()
        arrays = census["arrays"]
        assert arrays["count"] >= 1
        assert arrays["bytes"] >= 4096
        assert arrays["by_sharding"]["single"]["count"] >= 1
    finally:
        with dobj._registry_lock:
            dobj._registry.pop("census-test", None)


# ---------------------------------------------------------------------------
# in-process capture e2e (JAX_PLATFORMS=cpu)
# ---------------------------------------------------------------------------

def test_capture_in_process_attributes_jitted_steps(tmp_path):
    """The core acceptance lane, single-process: trace a jitted step
    loop and get device-op lanes plus a per-step breakdown whose step
    numbers continue the pre-capture counter with nonzero execute
    time."""
    import jax
    import jax.numpy as jnp

    device_trace.reset_phase_windows_for_testing()
    x = jnp.ones((256, 256), jnp.float32)
    raw_step = jax.jit(lambda a: jnp.tanh(a @ a))
    wrapped = device_trace.instrument_step(raw_step, rank=0)
    wrapped(x).block_until_ready()  # compile
    wrapped(x).block_until_ready()  # step 0
    wrapped(x).block_until_ready()  # step 1
    assert device_trace.current_step() == 2

    stop = threading.Event()

    def pump():
        while not stop.is_set():
            wrapped(x).block_until_ready()
            time.sleep(0.005)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        out = device_trace.capture(duration_s=0.8,
                                   out_dir=str(tmp_path))
    finally:
        stop.set()
        t.join(10)
        device_trace.reset_phase_windows_for_testing()

    assert not out.get("error"), out
    assert out["summary"]["device_events"] > 0
    # Step attribution: rows carry the post-warmup step numbers (the
    # first two steps ran before the capture window) and real device
    # execute time lands on them.
    assert out["steps"], out["summary"]
    assert all(row["step"] >= 2 for row in out["steps"])
    exec_rows = [row for row in out["steps"] if row["execute_ms"] > 0]
    assert exec_rows, out["steps"]
    assert any(row["top_ops"] for row in exec_rows)
    # Device lanes are wall-clock anchored inside the capture window.
    pid = os.getpid()
    dev = [ln for ln in out["lanes"] if ln["cat"] == f"device:{pid}"]
    assert dev
    assert all(out["t0"] - 1.0 <= ln["ts"] <= out["t1"] + 1.0
               for ln in dev)
    # Host sampler lanes rode along on the same clock.
    assert any(ln["cat"].startswith(f"host:{pid}:")
               for ln in out["host_lanes"])
    # The raw gz was retained on disk and re-parses standalone.
    assert out["trace_path"] and os.path.exists(out["trace_path"])
    reparsed = device_trace.parse_trace(out["trace_gz"])
    assert not reparsed.get("error")
    assert reparsed["summary"]["device_events"] > 0


def test_concurrent_capture_rejected(tmp_path):
    res = {}

    def bg():
        res["out"] = device_trace.capture(duration_s=1.2,
                                          out_dir=str(tmp_path))

    t = threading.Thread(target=bg)
    t.start()
    time.sleep(0.3)
    out2 = device_trace.capture(duration_s=0.2)
    t.join(60)
    assert out2.get("error") and "already in progress" in out2["error"]
    assert not res["out"].get("error"), res["out"]


# ---------------------------------------------------------------------------
# cluster e2e
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def trace_cluster():
    ray_tpu.init(num_cpus=3, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def _stepper(seconds):
    """A worker-side instrumented jitted step loop (the workload the
    acceptance criteria trace)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import device_trace as dt

    x = jnp.ones((256, 256), jnp.float32)
    step = dt.instrument_step(jax.jit(lambda a: jnp.tanh(a @ a)),
                              rank=0)
    t0 = time.monotonic()
    n = 0
    while time.monotonic() - t0 < seconds:
        step(x).block_until_ready()
        n += 1
        time.sleep(0.005)
    return n


def test_cluster_capture_merged_timeline(trace_cluster, tmp_path):
    """The tier-1 acceptance lane: `ray_tpu profile --device` against a
    worker running an instrumented jitted train step produces a merged
    timeline with host sampler lanes AND device:<pid> XLA-op lanes,
    plus a per-step breakdown with nonzero execute time on the right
    step numbers."""
    from ray_tpu.util import state as ust

    ref = _stepper.remote(20.0)
    task_hex = ref.id.task_id().hex()

    def running():
        rows = ust.list_tasks(
            filters=[("task_id", "contains", task_hex)])
        return any(r["state"] == "RUNNING" and r.get("worker_id")
                   for r in rows)

    _wait_for(running, desc="stepper RUNNING at the head")
    time.sleep(1.0)  # let the jit warm up so the window sees steps

    reply = device_trace.capture_cluster("task", task_hex,
                                         duration_s=1.0)
    assert not reply.get("error"), reply
    (entry,) = reply["entries"]
    assert not entry.get("error"), entry
    assert entry["source"].startswith("worker:")
    wpid = entry["pid"]
    assert entry["summary"]["device_events"] > 0
    assert any(ln["cat"] == f"device:{wpid}" for ln in entry["lanes"])
    exec_rows = [r for r in entry["steps"] if r["execute_ms"] > 0]
    assert exec_rows, entry["steps"]
    # Step numbers advanced past the warm-up steps the worker ran
    # before the capture window opened.
    assert all(r["step"] >= 1 for r in exec_rows)
    # The worker-targeted path resolves the same worker.
    reply2 = device_trace.capture_cluster("worker",
                                          entry["worker_id"],
                                          duration_s=0.3)
    assert not reply2.get("error"), reply2
    assert reply2["entries"][0]["worker_id"] == entry["worker_id"]

    # File outputs: raw gz + ops.json per source, merged timeline with
    # BOTH host sampler lanes and device lanes on one axis.
    out = str(tmp_path / "trace")
    manifest = device_trace.write_trace_outputs(reply, out)
    assert manifest["sources"] == [entry["source"]]
    assert manifest["device_events"] > 0
    assert any(r["execute_ms"] > 0 for r in manifest["steps"])
    names = os.listdir(out)
    assert any(n.endswith(".trace.json.gz") for n in names)
    assert any(n.endswith(".ops.json") for n in names)
    html = open(manifest["timeline"]).read()
    assert f"device:{wpid}" in html
    assert f"host:{wpid}:" in html
    with open(os.path.join(out, "trace.json")) as f:
        saved = json.load(f)
    assert saved["steps"] and saved["sources"]
    # The retained raw gz re-parses standalone (Perfetto-compatible
    # file really is the trace, not a placeholder).
    gz_name = next(n for n in names if n.endswith(".trace.json.gz"))
    with open(os.path.join(out, gz_name), "rb") as f:
        reparsed = device_trace.parse_trace(f.read())
    assert not reparsed.get("error")
    assert ray_tpu.get(ref, timeout=120) > 0


def test_cluster_capture_unknown_target(trace_cluster):
    reply = device_trace.capture_cluster("worker", "ffffffffffff",
                                         duration_s=0.2)
    assert reply.get("error")
    assert reply["entries"] == []
    reply = device_trace.capture_cluster("bogus-kind",
                                         duration_s=0.2)
    assert "unknown kind" in (reply.get("error") or "")


def test_debug_bundle_trace_section(trace_cluster, tmp_path):
    from ray_tpu.util import debug as udebug

    out = str(tmp_path / "bundle")
    manifest = udebug.write_debug_bundle(out, profile_duration_s=0,
                                         trace_duration_s=0.3)
    assert "trace" in manifest, manifest["errors"]
    assert "head" in manifest["trace"]["sources"]
    tdir = os.path.join(out, "trace")
    names = os.listdir(tdir)
    assert "timeline.html" in names and "trace.json" in names
    assert any(n.endswith(".ops.json") for n in names)


def test_worker_killed_mid_capture_yields_error_entry(trace_cluster):
    """Chaos lane: SIGKILL the target worker while its device-trace
    capture is in flight. The fan-out must come back with a per-source
    error entry — no hang, no parser crash on the never-delivered
    trace."""
    from ray_tpu.util import state as ust

    @ray_tpu.remote(max_retries=0)
    def hold(seconds):
        time.sleep(seconds)
        return os.getpid()

    ref = hold.remote(30.0)
    task_hex = ref.id.task_id().hex()

    def worker_of_task():
        rows = ust.list_tasks(
            filters=[("task_id", "contains", task_hex)])
        for r in rows:
            if r["state"] == "RUNNING" and r.get("worker_id"):
                return r["worker_id"]
        return None

    _wait_for(lambda: worker_of_task() is not None,
              desc="hold task RUNNING")
    wid = worker_of_task()
    pid = next(w["pid"] for w in ust.list_workers()
               if w["worker_id"].startswith(wid))

    res = {}

    def fanout():
        res["reply"] = device_trace.capture_cluster(
            "worker", wid, duration_s=3.0, timeout_s=20.0)

    t = threading.Thread(target=fanout, daemon=True)
    t.start()
    time.sleep(1.0)  # let start_trace begin in the worker
    os.kill(pid, signal.SIGKILL)
    t.join(60)
    assert not t.is_alive(), "fan-out hung past the worker's death"
    reply = res["reply"]
    # Either the head resolved the target before it died (per-source
    # error entry) or the connection dropped mid-call — both must
    # surface as a structured error, never a hang or an exception.
    if reply.get("error"):
        assert reply["entries"] == []
    else:
        (entry,) = reply["entries"]
        assert entry.get("error"), entry
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=60)
