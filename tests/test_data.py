"""Tests for ray_tpu.data (reference test strategy:
python/ray/data/tests/test_map.py, test_sort.py, test_consumption.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def ray_data_cluster():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def test_range_take_count(ray_data_cluster):
    ds = rd.range(100)
    assert ds.count() == 100
    assert ds.take(5) == [0, 1, 2, 3, 4]
    assert ds.take_all() == list(range(100))


def test_map(ray_data_cluster):
    ds = rd.range(20).map(lambda x: x * 2)
    assert ds.take_all() == [2 * i for i in range(20)]


def test_map_batches_fusion(ray_data_cluster):
    ds = (rd.range(50)
          .map_batches(lambda b: {"item": b["item"] + 1})
          .map_batches(lambda b: {"item": b["item"] * 3}))
    from ray_tpu.data.plan import fuse_plan, MapStage

    stages = fuse_plan(ds._op)
    map_stages = [s for s in stages if isinstance(s, MapStage)]
    assert len(map_stages) == 1  # fused
    assert len(map_stages[0].transforms) == 2
    assert ds.take_all() == [(i + 1) * 3 for i in range(50)]


def test_filter_flat_map(ray_data_cluster):
    ds = rd.range(20).filter(lambda x: x % 2 == 0)
    assert ds.take_all() == [i for i in range(20) if i % 2 == 0]
    ds2 = rd.from_items([1, 2]).flat_map(lambda x: [x, x * 10])
    assert ds2.take_all() == [1, 10, 2, 20]


def test_from_items_dicts(ray_data_cluster):
    items = [{"a": i, "b": i * 2} for i in range(10)]
    ds = rd.from_items(items)
    rows = ds.take_all()
    assert rows[3]["a"] == 3 and rows[3]["b"] == 6
    assert set(ds.schema()) == {"a", "b"}


def test_repartition(ray_data_cluster):
    ds = rd.range(100, parallelism=4).repartition(7)
    assert ds.num_blocks() == 7
    assert ds.take_all() == list(range(100))


def test_random_shuffle(ray_data_cluster):
    ds = rd.range(100).random_shuffle(seed=42)
    rows = ds.take_all()
    assert sorted(rows) == list(range(100))
    assert rows != list(range(100))
    rows2 = rd.range(100).random_shuffle(seed=42).take_all()
    assert rows == rows2  # deterministic given seed


def test_sort(ray_data_cluster):
    rng = np.random.default_rng(0)
    vals = rng.permutation(200).tolist()
    ds = rd.from_items(vals, parallelism=5).sort()
    assert ds.take_all() == sorted(vals)
    ds_desc = rd.from_items(vals, parallelism=5).sort(descending=True)
    assert ds_desc.take_all() == sorted(vals, reverse=True)


def test_sort_by_key(ray_data_cluster):
    items = [{"k": i % 5, "v": i} for i in range(50)]
    ds = rd.from_items(items).sort("k")
    ks = [r["k"] for r in ds.take_all()]
    assert ks == sorted(ks)


def test_limit_union_zip(ray_data_cluster):
    assert rd.range(100).limit(7).take_all() == list(range(7))
    u = rd.range(3).union(rd.range(3))
    assert u.take_all() == [0, 1, 2, 0, 1, 2]
    z = rd.range(10).zip(rd.range(10).map(lambda x: x * 10))
    rows = z.take_all()
    assert rows[2] == {"item": 2, "item_1": 20}


def test_aggregates(ray_data_cluster):
    ds = rd.range(10)
    assert ds.sum() == 45
    assert ds.min() == 0
    assert ds.max() == 9
    assert ds.mean() == 4.5


def test_groupby(ray_data_cluster):
    items = [{"k": i % 3, "v": float(i)} for i in range(30)]
    out = rd.from_items(items).groupby("k").sum("v").take_all()
    got = {r["k"]: r["sum(v)"] for r in out}
    expect = {}
    for r in items:
        expect[r["k"]] = expect.get(r["k"], 0) + r["v"]
    assert got == expect
    counts = rd.from_items(items).groupby("k").count().take_all()
    assert all(r["count()"] == 10 for r in counts)


def test_iter_batches(ray_data_cluster):
    ds = rd.range(100)
    batches = list(ds.iter_batches(batch_size=32))
    assert [len(b) for b in batches] == [32, 32, 32, 4]
    batches = list(ds.iter_batches(batch_size=32, drop_last=True))
    assert [len(b) for b in batches] == [32, 32, 32]


def test_iter_batches_jax(ray_data_cluster):
    import jax

    ds = rd.range_tensor(16, shape=(4,))
    batches = list(ds.iter_batches(batch_size=8, batch_format="jax"))
    assert isinstance(batches[0]["data"], jax.Array)
    assert batches[0]["data"].shape == (8, 4)


def test_local_shuffle_buffer_batch_contract(ray_data_cluster):
    # Buffer larger than the dataset: batches must still honor batch_size.
    ds = rd.range(100)
    batches = list(ds.iter_batches(batch_size=32,
                                   local_shuffle_buffer_size=10_000,
                                   local_shuffle_seed=0))
    assert [len(b) for b in batches] == [32, 32, 32, 4]
    flat = [x for b in batches for x in b.tolist()]
    assert sorted(flat) == list(range(100))
    assert flat != list(range(100))  # actually shuffled
    dropped = list(ds.iter_batches(batch_size=32, drop_last=True,
                                   local_shuffle_buffer_size=10_000))
    assert [len(b) for b in dropped] == [32, 32, 32]


def test_multi_column_agg_requires_on(ray_data_cluster):
    ds = rd.from_items([{"a": i, "b": i} for i in range(5)])
    with pytest.raises(ValueError, match="multiple columns"):
        ds.mean()
    assert ds.mean(on="a") == 2.0


def test_split_streaming_split(ray_data_cluster):
    splits = rd.range(100, parallelism=4).split(2, equal=True)
    assert [s.count() for s in splits] == [50, 50]
    its = rd.range(100, parallelism=4).streaming_split(4, equal=True)
    assert sum(it.count() for it in its) == 100


def test_file_roundtrip(ray_data_cluster, tmp_path):
    items = [{"a": i, "b": float(i) * 0.5} for i in range(40)]
    ds = rd.from_items(items, parallelism=3)
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 40
    assert sorted(r["a"] for r in back.take_all()) == list(range(40))
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 40
    ds.write_json(str(tmp_path / "json"))
    assert rd.read_json(str(tmp_path / "json")).count() == 40


def test_read_text(ray_data_cluster, tmp_path):
    p = tmp_path / "t.txt"
    p.write_text("a\nbb\nccc\n")
    assert [r["text"] for r in rd.read_text(str(p)).take_all()] == \
        ["a", "bb", "ccc"]


def test_column_ops(ray_data_cluster):
    ds = rd.from_items([{"a": i} for i in range(5)])
    ds = ds.add_column("b", lambda b: b["a"] * 2)
    assert ds.take(1)[0] == {"a": 0, "b": 0}
    assert set(ds.select_columns(["b"]).schema()) == {"b"}
    assert set(ds.drop_columns(["b"]).schema()) == {"a"}
    renamed = ds.rename_columns({"a": "x"})
    assert set(renamed.schema()) == {"x", "b"}


def test_map_batches_actor_compute(ray_data_cluster):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return {"item": batch["item"] + self.c}

    ds = rd.range(40).map_batches(
        AddConst, fn_constructor_args=(100,), compute="actors",
        concurrency=2)
    assert sorted(ds.take_all()) == [i + 100 for i in range(40)]


def test_materialize_and_stats(ray_data_cluster):
    ds = rd.range(50).map(lambda x: x + 1).materialize()
    st = ds.stats()
    assert st["num_rows"] == 50
    assert ds.take_all() == [i + 1 for i in range(50)]


def test_train_test_split(ray_data_cluster):
    tr, te = rd.range(100).train_test_split(0.2)
    assert tr.count() == 80 and te.count() == 20


def test_stats_per_operator(ray_data_cluster):
    st = (rd.range(60, parallelism=4)
          .map(lambda x: x + 1)
          .random_shuffle(seed=0)
          .stats())
    assert st["num_rows"] == 60
    names = [s["name"] for s in st["stages"]]
    assert any(n.startswith("Read") for n in names)
    assert any("Map" in n for n in names)
    assert any("RandomShuffle" in n for n in names)
    # Every stage saw all the rows and recorded remote exec time.
    for s in st["stages"]:
        assert s["num_rows"] == 60
        assert s["task_exec_s"] > 0
        assert s["driver_wall_s"] >= 0
    assert st["total_wall_s"] > 0
    assert "Operator" in st["summary"] and "Total wall" in st["summary"]


def test_stats_actor_compute(ray_data_cluster):
    class Ident:
        def __call__(self, batch):
            return batch

    st = (rd.range(20, parallelism=2)
          .map_batches(Ident, compute="actors", concurrency=1)
          .stats())
    map_stage = [s for s in st["stages"] if "MapBatches" in s["name"]][0]
    assert map_stage["num_blocks"] == 2
    assert map_stage["task_exec_s"] > 0


def test_data_context_byte_backpressure(ray_data_cluster):
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    old_bytes, old_blocks = ctx.max_in_flight_bytes, ctx.max_in_flight_blocks
    try:
        # Tiny byte budget: only ~1 task in flight at a time, but the
        # pipeline still completes correctly (always-admit-one rule).
        ctx.max_in_flight_bytes = 64
        out = sorted(rd.range(100, parallelism=8)
                     .map(lambda x: x + 1).take_all())
        assert out == [i + 1 for i in range(100)]
    finally:
        ctx.max_in_flight_bytes = old_bytes
        ctx.max_in_flight_blocks = old_blocks


def test_data_context_validation():
    from ray_tpu.data.context import DataContext

    import pytest as _pytest

    with _pytest.raises(ValueError):
        DataContext(shuffle_strategy="sideways")
    with _pytest.raises(ValueError):
        DataContext(max_in_flight_blocks=0)
