"""Object recovery via lineage reconstruction.

Reference: src/ray/core_worker/object_recovery_manager.h:41 (algorithm
:63-72) — on loss of every copy of an owned shm object, the owner
resubmits the creating task (retained under a byte budget,
task_manager.h:202) and the get() transparently returns the rebuilt
value."""

import numpy as np
import pytest

import ray_tpu

BIG = 200_000  # float64s -> ~1.6MB, well over the inline cutoff


def _delete_local_copies(ref):
    """Simulate losing every copy: delete from the node store directly
    WITHOUT telling the owner (as an eviction/crash would)."""
    from ray_tpu.core import native_store, object_store

    arena = native_store.get_attached_arena()
    if arena is not None:
        arena.delete(ref.id.binary())
    object_store._unlink_segment(ref.id.hex())
    object_store.spill_delete(ref.id)


def test_get_recovers_lost_object(ray_start_isolated):
    calls = []

    @ray_tpu.remote(max_retries=1)
    def produce(tag):
        return np.full(BIG, 3.5)

    ref = produce.remote("a")
    first = ray_tpu.get(ref, timeout=120)
    assert float(first[0]) == 3.5
    del first

    _delete_local_copies(ref)

    # All copies gone; get() must transparently resubmit and recover.
    again = ray_tpu.get(ref, timeout=180)
    assert again.shape == (BIG,)
    assert float(again[-1]) == 3.5


def test_recovery_survives_worker_churn(ray_start_isolated):
    """The original producer worker being long gone must not matter."""

    @ray_tpu.remote
    def produce():
        return np.arange(BIG, dtype=np.float64)

    ref = produce.remote()
    assert float(ray_tpu.get(ref, timeout=120)[7]) == 7.0

    @ray_tpu.remote(max_retries=1)
    def die():
        import os

        os._exit(1)

    with pytest.raises(ray_tpu.exceptions.WorkerCrashedError):
        ray_tpu.get(die.remote(), timeout=120)

    _delete_local_copies(ref)
    out = ray_tpu.get(ref, timeout=180)
    assert float(out[7]) == 7.0


def test_actor_results_are_not_recovered(ray_start_isolated):
    """Actor method results must NOT be rebuilt by re-execution (side
    effects would replay); loss surfaces as ObjectLostError."""

    @ray_tpu.remote
    class Producer:
        def make(self):
            return np.ones(BIG)

    p = Producer.remote()
    ref = p.make.remote()
    assert ray_tpu.get(ref, timeout=120).shape == (BIG,)

    _delete_local_copies(ref)
    with pytest.raises((ray_tpu.exceptions.ObjectLostError,
                        ray_tpu.exceptions.GetTimeoutError)):
        ray_tpu.get(ref, timeout=15)
    ray_tpu.kill(p)


def test_lineage_budget_eviction(ray_start_isolated):
    """Specs beyond the byte budget are evicted FIFO: old objects become
    unrecoverable, new ones stay recoverable."""
    from ray_tpu import api

    cw = api._global_worker

    @ray_tpu.remote(max_retries=1)
    def produce(i):
        return np.full(BIG, float(i))

    # Budget sized to hold ~3 specs, measured (spec encoding size is an
    # implementation detail that must not silently break eviction).
    from ray_tpu.core import serialization as _ser

    probe = produce.remote(0)
    ray_tpu.get(probe, timeout=120)
    spec_bytes = len(_ser.dumps_control(cw._lineage[probe.id][0]))
    budget = spec_bytes * 3 + spec_bytes // 2
    cw.config.max_lineage_bytes = budget

    refs = [produce.remote(i) for i in range(8)]
    for i, r in enumerate(refs):
        assert float(ray_tpu.get(r, timeout=120)[0]) == float(i)

    assert cw._lineage_bytes <= budget
    # The newest object must still be recoverable...
    _delete_local_copies(refs[-1])
    assert float(ray_tpu.get(refs[-1], timeout=180)[0]) == 7.0
    # ...while the oldest fell out of the budget.
    assert refs[0].id not in cw._lineage
    _delete_local_copies(refs[0])
    with pytest.raises((ray_tpu.exceptions.ObjectLostError,
                        ray_tpu.exceptions.GetTimeoutError)):
        ray_tpu.get(refs[0], timeout=15)
