"""Fake multi-node cluster tests (reference strategy:
python/ray/tests/test_multi_node.py via cluster_utils.Cluster)."""

import ray_tpu


def test_cluster_utils_multi_node():
    from ray_tpu.util import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1, "num_tpus": 0})
    try:
        cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)
        nodes = cluster.list_nodes()
        assert len(nodes) == 3
        total = ray_tpu.cluster_resources()
        assert total["CPU"] == 5.0

        @ray_tpu.remote
        def where():
            import os

            return os.getpid()

        # SPREAD strategy should run tasks despite multiple nodes.
        refs = [where.options(scheduling_strategy="SPREAD",
                              num_cpus=1).remote() for _ in range(4)]
        pids = ray_tpu.get(refs, timeout=120)
        assert len(pids) == 4
        cluster.remove_node(cluster.node_ids[0])
        # Dead nodes stay in the table with state DEAD (reference
        # semantics); only 2 remain alive.
        alive = [n for n in cluster.list_nodes() if n["state"] == "ALIVE"]
        assert len(alive) == 2
    finally:
        cluster.shutdown()


