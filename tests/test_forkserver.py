"""Tests for the worker forkserver (core/forkserver.py): spawn
protocol, liveness shim, orphan watchdog, and the WorkerPool
deferral/fallback logic."""

import os
import signal
import subprocess
import sys
import tempfile
import time

import pytest

from ray_tpu.core.forkserver import ForkedProc, ForkserverClient


@pytest.fixture(scope="module")
def fs_client():
    sd = tempfile.mkdtemp()
    os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
    client = ForkserverClient(sd, dict(os.environ))
    client.ensure_started()
    yield client, sd
    client.stop()


def test_spawn_is_fast_and_children_run(fs_client):
    client, sd = fs_client
    assert client.ready()
    log = os.path.join(sd, "logs", "w.log")
    t0 = time.perf_counter()
    # The child runs worker_main.main() which exits quickly without a
    # reachable head; what matters here is the fork round-trip.
    proc = client.spawn({"RAY_TPU_HEAD_HOST": "127.0.0.1",
                         "RAY_TPU_HEAD_PORT": "1",
                         "RAY_TPU_WORKER_ID": "00" * 14,
                         "RAY_TPU_NODE_ID": "00" * 14,
                         "RAY_TPU_SESSION_DIR": sd}, log)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"fork round-trip took {dt:.2f}s"
    assert proc.pid > 0
    proc.wait(timeout=30)  # child exits (no head to register with)
    assert proc.poll() is not None


def test_forked_proc_poll_and_kill(fs_client):
    client, sd = fs_client
    # A child that hangs forever (bogus head, long connect timeout).
    proc = client.spawn(
        {"RAY_TPU_HEAD_HOST": "10.255.255.1", "RAY_TPU_HEAD_PORT": "1",
         "RAY_TPU_WORKER_ID": "11" * 14, "RAY_TPU_NODE_ID": "00" * 14,
         "RAY_TPU_SESSION_DIR": sd,
         "RAY_TPU_RPC_CONNECT_TIMEOUT_S": "600"},
        os.path.join(sd, "logs", "hang.log"))
    assert proc.poll() is None  # alive
    proc.kill()
    deadline = time.time() + 10
    while proc.poll() is None and time.time() < deadline:
        time.sleep(0.05)
    assert proc.poll() is not None


def test_orphan_watchdog_exits_without_owner():
    """A forkserver whose launching process dies must exit on its own
    (crashed sessions must not leak preimported interpreters)."""
    sd = tempfile.mkdtemp()
    sock = os.path.join(sd, "fs.sock")
    # Launch through an intermediate python that dies immediately after
    # spawning the forkserver — the forkserver's ppid then changes.
    code = (
        "import os, subprocess, sys\n"
        f"p = subprocess.Popen([sys.executable, '-m', "
        f"'ray_tpu.core.forkserver', {sock!r}, str(os.getpid())], "
        "stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)\n"
        "print(p.pid, flush=True)\n"
    )
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    fs_pid = int(out.stdout.strip())

    def alive(pid: int) -> bool:
        # kill(pid, 0) succeeds on zombies; read the real state.
        try:
            with open(f"/proc/{pid}/stat") as f:
                return f.read().rsplit(")", 1)[1].split()[0] != "Z"
        except (FileNotFoundError, ProcessLookupError):
            return False

    # Prove the server actually reached its accept loop (a startup
    # crash would make the death-wait below pass vacuously) ...
    deadline = time.time() + 30
    while not os.path.exists(sock) and time.time() < deadline:
        assert alive(fs_pid), "forkserver died during startup"
        time.sleep(0.2)
    assert os.path.exists(sock), "forkserver never became ready"
    # ... then wait for the watchdog to notice the dead owner (2s poll).
    deadline = time.time() + 30
    while time.time() < deadline:
        if not alive(fs_pid):
            break  # exited
        time.sleep(0.3)
    else:
        os.kill(fs_pid, signal.SIGKILL)
        pytest.fail("orphaned forkserver did not exit")


def test_worker_pool_defers_then_uses_forkserver(monkeypatch):
    """_spawn_proc returns None (defer) while the forkserver is still
    preimporting and forks once it's ready; Popen when disabled."""
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.scheduler import WorkerPool

    sd = tempfile.mkdtemp()
    os.makedirs(os.path.join(sd, "logs"), exist_ok=True)
    pool = WorkerPool("127.0.0.1", 1, sd)
    node = NodeID.from_random()
    try:
        # First spawns defer while the forkserver preimports.
        first = pool.spawn(node)
        assert first is None or first.pid > 0
        deadline = time.time() + 60
        handle = None
        while handle is None and time.time() < deadline:
            handle = pool.spawn(node)
            if handle is None:
                time.sleep(0.2)
        assert handle is not None and handle.pid > 0
        # Disabled -> immediate cold Popen, no deferral.
        monkeypatch.setenv("RAY_TPU_WORKER_FORKSERVER", "0")
        from ray_tpu.core import config as config_mod

        config_mod._global_config = None  # re-read env
        pool2 = WorkerPool("127.0.0.1", 1, sd)
        h2 = pool2.spawn(node)
        assert h2 is not None and h2.pid > 0
        pool2.shutdown()
    finally:
        monkeypatch.delenv("RAY_TPU_WORKER_FORKSERVER", raising=False)
        from ray_tpu.core import config as config_mod

        config_mod._global_config = None
        pool.shutdown()
