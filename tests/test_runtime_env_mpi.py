"""Tests for the MPI runtime-env plugin (reference strategy:
python/ray/tests/test_runtime_env_mpi-style gang execution checks).

The image ships no MPI distribution, so these tests exercise the
built-in "simulated" launcher (plain subprocess gang with
RTPU_MPI_RANK/SIZE); the mpirun path shares everything but the spawn
call and is covered by the launcher-missing error test.
"""

import os

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def mpi_cluster():
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _rank_report(tag):
    # Runs on rank 0 INSIDE the gang child process.
    from ray_tpu.core.runtime_env_mpi import _detect_rank_size

    rank, size = _detect_rank_size()
    return {"tag": tag, "rank": rank, "size": size}


def test_task_runs_on_rank0_of_gang(mpi_cluster):
    fn = ray_tpu.remote(_rank_report).options(runtime_env={
        "mpi": {"args": ["-n", "3"], "launcher": "simulated"}})
    out = ray_tpu.get(fn.remote("hello"), timeout=120)
    assert out == {"tag": "hello", "rank": 0, "size": 3}


def test_worker_entry_runs_on_every_rank(mpi_cluster, tmp_path):
    # worker_entry is resolved by import inside each gang rank; write a
    # module that records its rank, shipped via env_vars PYTHONPATH.
    mod_dir = tmp_path / "mods"
    mod_dir.mkdir()
    out_dir = tmp_path / "ranks"
    out_dir.mkdir()
    (mod_dir / "gang_entry.py").write_text(
        "import os\n"
        "def bootstrap(rank, size):\n"
        f"    open(os.path.join({str(out_dir)!r}, str(rank)), 'w')"
        ".write(str(size))\n")

    def task(x):
        return x * 2

    fn = ray_tpu.remote(task).options(runtime_env={
        "env_vars": {"PYTHONPATH": str(mod_dir)},
        "mpi": {"args": ["-n", "4"], "launcher": "simulated",
                "worker_entry": "gang_entry.bootstrap"},
    })
    assert ray_tpu.get(fn.remote(21), timeout=120) == 42
    ranks = sorted(os.listdir(out_dir))
    assert ranks == ["0", "1", "2", "3"]
    assert all((out_dir / r).read_text() == "4" for r in ranks)


def test_task_exception_propagates(mpi_cluster):
    def boom():
        raise ValueError("inside the gang")

    fn = ray_tpu.remote(boom).options(runtime_env={
        "mpi": {"args": ["-n", "2"], "launcher": "simulated"}})
    with pytest.raises(Exception, match="inside the gang"):
        ray_tpu.get(fn.remote(), timeout=120)


def test_missing_launcher_is_setup_error(mpi_cluster):
    def nop():
        return 1

    fn = ray_tpu.remote(nop).options(runtime_env={
        "mpi": {"args": ["-n", "2"],
                "launcher": "definitely-not-a-real-mpirun"}})
    with pytest.raises(Exception, match="not found"):
        ray_tpu.get(fn.remote(), timeout=120)


def test_parse_np():
    from ray_tpu.core.runtime_env_mpi import _parse_np

    assert _parse_np(["-n", "4"]) == 4
    assert _parse_np(["-np", "8", "--oversubscribe"]) == 8
    assert _parse_np([]) == 1


def test_mpi_rejected_on_actors(mpi_cluster):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.options(runtime_env={
        "mpi": {"args": ["-n", "2"], "launcher": "simulated"}}).remote()
    with pytest.raises(Exception, match="normal tasks only"):
        ray_tpu.get(a.ping.remote(), timeout=60)


def test_parse_np_errors():
    from ray_tpu.core.runtime_env_mpi import _parse_np

    with pytest.raises(Exception, match="rank count"):
        _parse_np(["-n"])
    with pytest.raises(Exception, match="not an int"):
        _parse_np(["-np", "four"])
