"""Compiled DAG + shm channels (reference strategy:
python/ray/dag/tests/experimental/test_accelerated_dag.py — correctness
of compiled execution, teardown, and multi-actor pipelines;
python/ray/tests/test_channel.py — channel semantics)."""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode
from ray_tpu.experimental import ChannelClosed, ShmChannel


# ---------------------------------------------------------------------------
# channel unit tests (no cluster)
# ---------------------------------------------------------------------------


def test_channel_roundtrip_and_order():
    ch = ShmChannel.create(f"rtpu_t_{time.time_ns()}", nslots=4,
                           slot_bytes=4096)
    try:
        for i in range(10):  # wraps the 4-slot ring
            ch.write({"i": i})
            assert ch.read(timeout=5) == {"i": i}
    finally:
        ch.destroy()


def test_channel_backpressure_blocks_writer():
    ch = ShmChannel.create(f"rtpu_t_{time.time_ns()}", nslots=2,
                           slot_bytes=1024)
    try:
        ch.write(1)
        ch.write(2)
        with pytest.raises(TimeoutError):
            ch.write_bytes(b"x", timeout=0.2)  # ring full
        assert ch.read(timeout=5) == 1
        ch.write(3)  # slot freed
        assert ch.read(timeout=5) == 2
        assert ch.read(timeout=5) == 3
    finally:
        ch.destroy()


def test_channel_close_ends_stream():
    ch = ShmChannel.create(f"rtpu_t_{time.time_ns()}", nslots=2,
                           slot_bytes=1024)
    try:
        ch.write("last")
        ch.close()
        assert ch.read(timeout=5) == "last"  # drained before EOS
        with pytest.raises(ChannelClosed):
            ch.read(timeout=5)
    finally:
        ch.destroy()


def test_channel_threaded_pingpong():
    a = ShmChannel.create(f"rtpu_t_{time.time_ns()}", nslots=4,
                          slot_bytes=1 << 16)

    def echo():
        while True:
            try:
                v = a.read(timeout=10)
            except ChannelClosed:
                return

    t = threading.Thread(target=echo, daemon=True)
    t.start()
    for i in range(1000):
        a.write(i)
    a.close()
    t.join(timeout=10)
    assert not t.is_alive()
    a.destroy()


# ---------------------------------------------------------------------------
# compiled DAG over a cluster
# ---------------------------------------------------------------------------


@ray_tpu.remote
class Adder:
    def __init__(self, add):
        self.add = add

    def fwd(self, x):
        return x + self.add

    def combine(self, a, b):
        return a + b


def test_compiled_chain_matches_classic(ray_start):
    a = Adder.remote(1)
    b = Adder.remote(10)
    ray_tpu.get([a.fwd.remote(0), b.fwd.remote(0)], timeout=60)
    with InputNode() as inp:
        node = b.fwd.bind(a.fwd.bind(inp))
    classic = ray_tpu.get(node.execute(5), timeout=60)
    cd = node.experimental_compile()
    try:
        assert cd.execute(5, timeout=60) == classic == 16
        # Repeated ticks reuse the same channels — no per-call tasks.
        for i in range(50):
            assert cd.execute(i, timeout=60) == i + 11
    finally:
        cd.teardown()
    # The loop released the actors: plain calls work again.
    assert ray_tpu.get(a.fwd.remote(1), timeout=60) == 2


def test_compiled_join_two_upstreams(ray_start):
    a = Adder.remote(1)
    b = Adder.remote(2)
    c = Adder.remote(0)
    ray_tpu.get([x.fwd.remote(0) for x in (a, b, c)], timeout=60)
    with InputNode() as inp:
        node = c.combine.bind(a.fwd.bind(inp), b.fwd.bind(inp))
    cd = node.experimental_compile()
    try:
        # (x+1) + (x+2)
        assert cd.execute(0, timeout=60) == 3
        assert cd.execute(10, timeout=60) == 23
    finally:
        cd.teardown()


def test_compiled_large_values_overflow_to_store(ray_start):
    a = Adder.remote(1.0)
    ray_tpu.get(a.fwd.remote(0), timeout=60)
    with InputNode() as inp:
        node = a.fwd.bind(inp)
    cd = node.experimental_compile(buffer_size_bytes=4096)
    try:
        big = np.ones(100_000)  # ~800KB > 4KB slot: ships as a ref
        out = cd.execute(big, timeout=120)
        assert out.shape == big.shape
        assert float(out[0]) == 2.0
    finally:
        cd.teardown()


def test_compiled_rejects_plain_tasks(ray_start):
    @ray_tpu.remote
    def f(x):
        return x

    with InputNode() as inp:
        node = f.bind(inp)
    with pytest.raises(ValueError):
        node.experimental_compile()


def test_compiled_kwarg_nodes_are_wired(ray_start):
    a = Adder.remote(5)
    c = Adder.remote(0)
    ray_tpu.get([a.fwd.remote(0), c.fwd.remote(0)], timeout=60)
    with InputNode() as inp:
        # DAG node passed by KEYWORD — must ride a channel, not pickle
        # as a constant.
        node = c.combine.bind(0, b=a.fwd.bind(inp))
    cd = node.experimental_compile()
    try:
        assert cd.execute(1, timeout=60) == 6  # 0 + (1+5)
        assert cd.execute(10, timeout=60) == 15
    finally:
        cd.teardown()


def test_compiled_requires_input_edge(ray_start):
    a = Adder.remote(1)
    ray_tpu.get(a.fwd.remote(0), timeout=60)
    node = a.fwd.bind(3)  # constant-only graph: nothing drives ticks
    with pytest.raises(ValueError):
        node.experimental_compile()


@ray_tpu.remote
class Flaky:
    """Raises on demand — exercises in-loop error propagation."""

    def step(self, x):
        if isinstance(x, int) and x < 0:
            raise ValueError(f"bad input {x}")
        return x * 2

    def tail(self, x):
        return x + 1


def test_compiled_method_error_propagates_and_dag_survives(ray_start):
    """Advisor r4 (medium): a user-method exception must surface from
    execute() as the original error — not a ChannelClosed/Timeout — and
    the DAG must stay alive for subsequent ticks (reference:
    compiled_dag_node.py wraps per-execution errors)."""
    a = Flaky.remote()
    ray_tpu.get(a.step.remote(0), timeout=60)
    with InputNode() as inp:
        node = a.step.bind(inp)
    cd = node.experimental_compile()
    try:
        assert cd.execute(3, timeout=60) == 6
        with pytest.raises(ValueError, match="bad input -1"):
            cd.execute(-1, timeout=60)
        # The pinned loop survived the error.
        assert cd.execute(4, timeout=60) == 8
    finally:
        cd.teardown()


def test_compiled_error_forwards_through_downstream(ray_start):
    """An upstream error skips downstream methods and reaches the
    driver intact."""
    a = Flaky.remote()
    b = Flaky.remote()
    ray_tpu.get([a.step.remote(0), b.step.remote(0)], timeout=60)
    with InputNode() as inp:
        node = b.tail.bind(a.step.bind(inp))
    cd = node.experimental_compile()
    try:
        assert cd.execute(2, timeout=60) == 5
        with pytest.raises(ValueError, match="bad input -7"):
            cd.execute(-7, timeout=60)
        assert cd.execute(1, timeout=60) == 3
    finally:
        cd.teardown()
